"""L2: fault injection — the nemesis.

Counterpart of jepsen.nemesis (jepsen/src/jepsen/nemesis.clj): a Nemesis
has setup/invoke/teardown (nemesis.clj:10-15) and responds to :info ops
from the generator by breaking the system. Grudge functions compute who
stops talking to whom (nemesis.clj:121-226); `compose` routes ops to
children by :f (nemesis.clj:228-311).
"""

from __future__ import annotations

import logging
import random
from typing import Callable, Iterable

from .. import control, net as jnet
from ..control import util as cutil
from ..util import majority, timeout_call

log = logging.getLogger(__name__)


class Nemesis:
    # fs this nemesis handles — used by compose routing (Reflection/fs,
    # nemesis.clj:17-20).
    fs: frozenset = frozenset()

    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass


class NoopNemesis(Nemesis):
    def invoke(self, test, op):
        return {**op, "type": "info"}


def noop() -> Nemesis:
    return NoopNemesis()


class Timeout(Nemesis):
    """Bounds a flaky nemesis's ops; timed-out ops get :value :timeout
    (nemesis.clj:105-119)."""

    def __init__(self, timeout_s: float, nemesis: Nemesis):
        self.timeout_s = timeout_s
        self.nemesis = nemesis
        self.fs = nemesis.fs

    def setup(self, test):
        self.nemesis = self.nemesis.setup(test)
        return self

    def invoke(self, test, op):
        return timeout_call(self.timeout_s,
                            lambda: self.nemesis.invoke(test, op),
                            default={**op, "type": "info",
                                     "value": "timeout"})

    def teardown(self, test):
        self.nemesis.teardown(test)


# ---------------------------------------------------------------------------
# Grudges: {node: set of nodes whose traffic it drops}
# ---------------------------------------------------------------------------

def bisect(coll: list) -> list[list]:
    """Split in half, smaller half first (nemesis.clj:121-125)."""
    mid = len(coll) // 2
    return [list(coll[:mid]), list(coll[mid:])]


def split_one(coll: list, loner=None) -> list[list]:
    """One node versus the rest (nemesis.clj:126-131)."""
    loner = loner if loner is not None else random.choice(list(coll))
    return [[loner], [x for x in coll if x != loner]]


def complete_grudge(components: Iterable[Iterable]) -> dict:
    """No node may talk outside its component (nemesis.clj:133-146)."""
    comps = [set(c) for c in components]
    universe = set().union(*comps) if comps else set()
    grudge = {}
    for comp in comps:
        for node in comp:
            grudge[node] = universe - comp
    return grudge


def bridge(nodes: list) -> dict:
    """Two halves with one bridge node seeing both (nemesis.clj:147-158)."""
    comps = bisect(list(nodes))
    b = comps[1][0]
    grudge = complete_grudge(comps)
    grudge.pop(b, None)
    return {node: snubbed - {b} for node, snubbed in grudge.items()}


def majorities_ring(nodes: list) -> dict:
    """Every node sees a majority, but no two see the same one
    (nemesis.clj:205-226)."""
    nodes = list(nodes)
    U = set(nodes)
    n = len(nodes)
    m = majority(n)
    ring = random.sample(nodes, n)
    grudge = {}
    for i in range(n):
        maj = [ring[(i + j) % n] for j in range(m)]
        holder = maj[len(maj) // 2]
        grudge[holder] = U - set(maj)
    return grudge


# ---------------------------------------------------------------------------
# Partitioners
# ---------------------------------------------------------------------------

class Partitioner(Nemesis):
    """:start cuts links per the grudge; :stop heals
    (nemesis.clj:160-186)."""

    fs = frozenset({"start", "stop"})

    def __init__(self, grudge_fn: Callable[[list], dict] | None = None):
        self.grudge_fn = grudge_fn

    def setup(self, test):
        jnet.net_for(test).heal(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f == "start":
            grudge = op.get("value")
            if grudge is None:
                if self.grudge_fn is None:
                    raise ValueError(f"op {op!r} needs a grudge :value")
                grudge = self.grudge_fn(list(test.get("nodes", [])))
            jnet.net_for(test).drop_all(test, grudge)
            return {**op, "type": "info", "value": ["isolated", grudge]}
        if f == "stop":
            jnet.net_for(test).heal(test)
            return {**op, "type": "info", "value": "network-healed"}
        raise ValueError(f"unknown partitioner op {op!r}")

    def teardown(self, test):
        jnet.net_for(test).heal(test)


def partitioner(grudge_fn=None) -> Nemesis:
    return Partitioner(grudge_fn)


def partition_halves() -> Nemesis:
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Nemesis:
    def grudge(nodes):
        nodes = random.sample(list(nodes), len(nodes))
        return complete_grudge(bisect(nodes))

    return Partitioner(grudge)


def partition_random_node() -> Nemesis:
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Nemesis:
    return Partitioner(majorities_ring)


# ---------------------------------------------------------------------------
# Compose
# ---------------------------------------------------------------------------

class Compose(Nemesis):
    """Routes ops to child nemeses by :f (nemesis.clj:228-311). Takes a
    mapping of f-routers to nemeses: a router is a set of fs (identity
    routing) or a dict rewriting outer fs to inner fs."""

    def __init__(self, children: dict):
        self.children = dict(children)
        fs: set = set()
        for router in self.children:
            fs |= set(router)
        self.fs = frozenset(fs)

    def setup(self, test):
        self.children = {r: n.setup(test) for r, n in self.children.items()}
        return self

    def invoke(self, test, op):
        f = op.get("f")
        for router, nem in self.children.items():
            if f in router:
                inner_f = router[f] if isinstance(router, dict) else f
                res = nem.invoke(test, {**op, "f": inner_f})
                return {**res, "f": f}
        raise ValueError(f"no nemesis handles f={f!r}")

    def teardown(self, test):
        for nem in self.children.values():
            nem.teardown(test)


def compose(children: dict | list) -> Nemesis:
    """compose({frozenset({"start","stop"}): partitioner(...), ...}) or
    compose([nem1, nem2]) using each nemesis's declared fs."""
    if isinstance(children, dict):
        return Compose(children)
    return Compose({frozenset(n.fs): n for n in children})


# ---------------------------------------------------------------------------
# Process-level faults
# ---------------------------------------------------------------------------

class NodeStartStopper(Nemesis):
    """:start runs stop! on targeted nodes; :stop runs start! everywhere
    affected (node-start-stopper, nemesis.clj:335-379)."""

    fs = frozenset({"start", "stop"})

    def __init__(self, targeter: Callable[[list], list],
                 stop_fn: Callable[[dict, str], object],
                 start_fn: Callable[[dict, str], object]):
        self.targeter = targeter
        self.stop_fn = stop_fn
        self.start_fn = start_fn
        self.affected: set = set()

    def invoke(self, test, op):
        f = op.get("f")
        if f == "start":
            targets = list(self.targeter(list(test.get("nodes", []))))
            res = control.on_nodes(test, self.stop_fn, targets)
            self.affected |= set(targets)
            return {**op, "type": "info", "value": [f, dict(res)]}
        if f == "stop":
            nodes = sorted(self.affected)
            res = control.on_nodes(test, self.start_fn, nodes)
            self.affected.clear()
            return {**op, "type": "info", "value": [f, dict(res)]}
        raise ValueError(f"unknown op {op!r}")


def hammer_time(process_name: str, targeter=None) -> Nemesis:
    """SIGSTOP/SIGCONT a process on targeted nodes
    (nemesis.clj:380-394)."""
    targeter = targeter or (lambda nodes: [random.choice(nodes)])

    def stop(test, node):
        cutil.signal(control.current_session().su(), process_name, "STOP")
        return "paused"

    def start(test, node):
        cutil.signal(control.current_session().su(), process_name, "CONT")
        return "resumed"

    return NodeStartStopper(targeter, stop, start)


class TruncateFile(Nemesis):
    """Truncates a file by a few bytes on targeted nodes — corrupting
    logs/segments (nemesis.clj:396-422)."""

    fs = frozenset({"truncate"})

    def __init__(self, path: str, bytes_: int = 100):
        self.path = path
        self.bytes = bytes_

    def invoke(self, test, op):
        targets = op.get("value") or [random.choice(test["nodes"])]

        def trunc(t, node):
            control.current_session().su().exec(
                "truncate", "-c", "-s", f"-{self.bytes}", self.path)
            return "truncated"

        res = control.on_nodes(test, trunc, list(targets))
        return {**op, "type": "info", "value": dict(res)}


def truncate_file(path: str, bytes_: int = 100) -> Nemesis:
    return TruncateFile(path, bytes_)
