"""Clock faults: bumps, strobes, resets.

Counterpart of jepsen.nemesis.time (jepsen/src/jepsen/nemesis/time.clj):
ships the native C++ helpers (native/bump_time.cc, strobe_time.cc — our
re-implementations of the reference's resources/bump-time.c and
strobe-time.c) to each node, compiles them with the node's compiler
(time.clj:15-53), and drives them through nemesis ops:

  {:f :reset,  :value [nodes...]}          ntpdate back to true time
  {:f :bump,   :value {node: delta-ms}}    one-shot clock jumps
  {:f :strobe, :value {node: {...}}}       rapid clock flapping
  {:f :check-offsets}                      annotate clock offsets
"""

from __future__ import annotations

import logging
import os.path
import random

from .. import control
from ..control import util as cutil
from . import Nemesis

log = logging.getLogger(__name__)

BIN_DIR = "/opt/jepsen"
NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")

HELPERS = ("bump_time", "strobe_time")


def install_helpers(test: dict, node: str) -> None:
    """Upload + compile the clock helpers on a node (time.clj:15-53)."""
    sess = control.current_session()
    su = sess.su()
    su.exec("mkdir", "-p", BIN_DIR)
    for name in HELPERS:
        src = os.path.join(NATIVE_DIR, f"{name}.cc")
        dest_src = f"{BIN_DIR}/{name}.cc"
        dest_bin = f"{BIN_DIR}/{name.replace('_', '-')}"
        sess.upload(src, "/tmp/" + os.path.basename(src))
        su.exec("mv", "/tmp/" + os.path.basename(src), dest_src)
        su.exec(control.Lit(
            f"g++ -O2 -o {dest_bin} {dest_src} 2>/dev/null || "
            f"gcc -O2 -x c++ -o {dest_bin} {dest_src} -lstdc++"))


def reset_time(test: dict, node: str) -> str:
    """Snap the clock back to true time (time.clj:72-76)."""
    return control.current_session().su().exec(
        control.Lit("ntpdate -p 1 -b pool.ntp.org || "
                    "ntpdate -p 1 -b time.google.com"))


def bump_time(test: dict, node: str, delta_ms: float) -> str:
    return control.current_session().su().exec(
        f"{BIN_DIR}/bump-time", delta_ms)


def strobe_time(test: dict, node: str, delta_ms: float, period_ms: float,
                duration_s: float) -> str:
    return control.current_session().su().exec(
        f"{BIN_DIR}/strobe-time", delta_ms, period_ms, duration_s)


def clock_offset(test: dict, node: str) -> float:
    """Node wall-clock offset from the control host, in seconds."""
    import time as _t
    remote = float(control.current_session().exec("date", "+%s.%N"))
    return remote - _t.time()


class ClockNemesis(Nemesis):
    """Drives reset/bump/strobe/check-offsets ops (time.clj:90-140)."""

    fs = frozenset({"reset", "bump", "strobe", "check-offsets"})

    def setup(self, test):
        control.on_nodes(test, install_helpers)
        control.on_nodes(test, reset_time)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        v = op.get("value")
        if f == "reset":
            nodes = v or test.get("nodes", [])
            res = control.on_nodes(test, reset_time, list(nodes))
        elif f == "bump":
            res = control.on_nodes(
                test, lambda t, n: bump_time(t, n, v[n]), list(v))
        elif f == "strobe":
            res = control.on_nodes(
                test,
                lambda t, n: strobe_time(t, n, v[n]["delta"],
                                         v[n]["period"], v[n]["duration"]),
                list(v))
        elif f == "check-offsets":
            res = control.on_nodes(test, clock_offset)
            return {**op, "type": "info", "clock-offsets": dict(res)}
        else:
            raise ValueError(f"unknown clock op {op!r}")
        return {**op, "type": "info", "value": [f, dict(res)]}

    def teardown(self, test):
        try:
            control.on_nodes(test, reset_time)
        except Exception as e:
            log.warning("clock teardown failed: %s", e)


def clock_nemesis() -> Nemesis:
    return ClockNemesis()


# -- generators (time.clj:142-201) -----------------------------------------

def reset_gen(test=None, ctx=None):
    return {"type": "info", "f": "reset", "value": None}


def bump_gen(test, ctx):
    """Bump a random subset of nodes by ±2^2..2^18 ms (time.clj:155-172)."""
    nodes = list(test.get("nodes", []))
    random.shuffle(nodes)
    targets = nodes[: random.randint(1, max(1, len(nodes)))]
    delta = (2 ** random.randint(2, 18)) * random.choice([-1, 1])
    return {"type": "info", "f": "bump",
            "value": {n: delta for n in targets}}


def strobe_gen(test, ctx):
    """Strobe a random subset: delta ±2^2..2^18 ms, period 1-1024 ms,
    duration 0-32 s (time.clj:174-191)."""
    nodes = list(test.get("nodes", []))
    random.shuffle(nodes)
    targets = nodes[: random.randint(1, max(1, len(nodes)))]
    spec = {"delta": 2 ** random.randint(2, 18),
            "period": 2 ** random.randint(0, 10),
            "duration": random.randint(0, 32)}
    return {"type": "info", "f": "strobe", "value": {n: spec for n in targets}}


def clock_gen():
    """Mix of resets, bumps, strobes (time.clj:193-201)."""
    from .. import generator as gen
    return gen.mix([reset_gen, bump_gen, strobe_gen])


def set_time(t: float) -> str:
    """Set the current session's node clock to POSIX time t
    (nemesis.clj:313-316)."""
    return control.current_session().su().exec(
        "date", "+%s", "-s", f"@{int(t)}")


class ClockScrambler(Nemesis):
    """Randomizes every node's clock within a ±dt-second window on each
    invoke; teardown snaps them back to true time
    (nemesis.clj:318-333)."""

    fs = frozenset({"scramble"})

    def __init__(self, dt: float):
        self.dt = dt

    def invoke(self, test, op):
        import time as _time

        def scramble(t, node):
            return set_time(_time.time()
                            + random.randint(-int(self.dt), int(self.dt)))

        value = control.on_nodes(test, scramble)
        return {**op, "type": "info", "value": value}

    def teardown(self, test):
        import time as _time
        try:
            control.on_nodes(test,
                             lambda t, n: set_time(_time.time()))
        except Exception:
            log.warning("clock scrambler teardown failed", exc_info=True)


def clock_scrambler(dt: float) -> Nemesis:
    return ClockScrambler(dt)
