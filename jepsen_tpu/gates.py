"""jepsen_tpu.gates — THE registry of `JEPSEN_TPU_*` environment gates.

Every env var this package reads is declared here exactly once — name,
type, default, one doc line — and read only through the typed
accessors below. The rest of the package holds no raw
`os.environ`/`os.getenv` of a `JEPSEN_TPU_*` name: the self-hosted
linter (``python -m jepsen_tpu.cli lint``, rule JT-GATE-001) fails the
build on one, and rule JT-GATE-003/004 fail it when a registered gate
is missing from the README env-gate table (rendered from this registry
by `render_env_table`) or from test coverage. That closes the drift
loop that produced 21 ad-hoc gate reads with three different truthy
parses: a gate can no longer exist without a declaration, a doc row
and a test.

Parse semantics are normalized to two shapes (recorded per gate by
`kind` + `default`):

  * bool, default on  — unset or anything but ``"0"`` is True
    (the historical ``!= "0"`` convention of the default-on gates);
  * bool, default off — only a set, non-empty, non-``"0"`` value is
    True. This widens the old ``== "1"`` gates (STRICT, JAX_PROFILE,
    PIPELINE) to accept ``yes``/``true`` spellings, and FIXES
    ``JEPSEN_TPU_NO_NATIVE=0``, which the old truthy-string parse
    read as *disable native* (see MIGRATING.md);
  * int/float — parsed, falling back to the declared default on
    malformed values instead of crashing the run (the old
    ``int(os.environ[...])`` reads raised ValueError);
  * str — raw value, empty string treated as unset;
  * marker — not an env var at all: a protocol constant that shares
    the namespace (``JEPSEN_TPU_EC`` is the ssh exit-code marker
    string), registered so the name scanner and the README table can
    account for it.

This module is the ONE file where `os.environ` access to
`JEPSEN_TPU_*` names is sanctioned; `export`/`unset` are the writer
counterparts the CLI uses to hand a flag down to subprocesses.
Stdlib-only, import-cheap: every hot path reads gates at call time, so
tests can monkeypatch the env freely.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

PREFIX = "JEPSEN_TPU_"

#: Parse kinds a gate may declare.
KINDS = ("bool", "int", "float", "str", "marker")


class Gate:
    """One declared gate: name, kind, default, one doc line."""

    __slots__ = ("name", "kind", "default", "doc", "choices")

    def __init__(self, name: str, kind: str, default, doc: str,
                 choices: tuple[str, ...] | None = None):
        assert kind in KINDS, kind
        assert name.startswith(PREFIX), name
        self.name = name
        self.kind = kind
        self.default = default
        self.doc = doc
        self.choices = choices

    def parse(self, raw: str | None):
        """Typed value for a raw env string (None = unset)."""
        if self.kind == "marker":
            return self.default
        if raw is None or (raw == "" and self.kind != "bool"):
            return self.default
        if self.kind == "bool":
            if self.default:
                return raw != "0"
            return raw not in ("", "0")
        if self.kind == "int":
            try:
                return int(raw)
            except ValueError:
                log.debug("malformed %s=%r; using default %r",
                          self.name, raw, self.default)
                return self.default
        if self.kind == "float":
            try:
                return float(raw)
            except ValueError:
                log.debug("malformed %s=%r; using default %r",
                          self.name, raw, self.default)
                return self.default
        # str — stripped: a trailing space from a shell export or CI
        # YAML must not turn a valid choice into "unrecognized"
        raw = raw.strip()
        if raw == "":
            return self.default
        if self.choices is not None and raw not in self.choices:
            _warn_once(self.name, raw, self.choices)
            return self.default
        return raw

    def default_str(self) -> str:
        """The README-table rendering of the default."""
        if self.kind == "marker":
            return "—"
        if self.kind == "bool":
            return "`1`" if self.default else "off"
        if self.default is None or self.default == "":
            return "off"
        return f"`{self.default}`"


_warned: set[str] = set()


def _warn_once(name: str, raw: str, choices) -> None:
    if name in _warned:
        return
    _warned.add(name)
    want = "|".join(c for c in choices if c)
    log.warning("unrecognized %s=%r (want %s); using the default",
                name, raw, want)


# ---------------------------------------------------------------------------
# The registry. Ordering is the README table ordering.
# ---------------------------------------------------------------------------

GATES: dict[str, Gate] = {}


def _g(name: str, kind: str, default, doc: str,
       choices: tuple[str, ...] | None = None) -> None:
    assert name not in GATES, f"duplicate gate {name}"
    GATES[name] = Gate(name, kind, default, doc, choices)


# -- observability ----------------------------------------------------------
_g("JEPSEN_TPU_TRACE", "bool", True,
   "`0`: no trace/metrics files, no-op spans (<1µs each — the "
   "dp8-efficiency floor is unaffected)")
_g("JEPSEN_TPU_TRACE_MAX_EVENTS", "int", 200_000,
   "bounded tracer event buffer; overflow is counted "
   "(`dropped_events`), never silent")
_g("JEPSEN_TPU_WORKER_TRACE", "bool", True,
   "`0`: ingest pool workers record no spans and write no "
   "`trace-<pid>.jsonl` spools (the merged sweep trace then carries "
   "only parent-side tracks); moot when `JEPSEN_TPU_TRACE=0` — no "
   "tracer means no spools either way")
_g("JEPSEN_TPU_REPORT", "bool", False,
   "set: `analyze-store` writes the critical-path attribution report "
   "(`<store>/report.json` + `report.md`) at sweep end, as if "
   "`--report` were passed")
_g("JEPSEN_TPU_JAX_PROFILE", "bool", False,
   "`1`: wrap the run in a `jax.profiler` capture "
   "(`<run-dir>/jax-profile`; `--jax-profile` sets it)")
_g("JEPSEN_TPU_HEALTH_INTERVAL_S", "float", None,
   "live telemetry: write `<store>/health.json` atomically every this "
   "many seconds during a sweep (progress, robustness, throughput, "
   "heartbeat); unset/<=0 = off")
_g("JEPSEN_TPU_METRICS_PORT", "int", None,
   "serve `/metrics` (Prometheus text exposition) + `/healthz` (the "
   "health snapshot) on this port during a sweep; `0` binds an "
   "ephemeral port; unset = off")
_g("JEPSEN_TPU_EVENTS_MAX_BYTES", "int", None,
   "rotate `<store>/events.jsonl` once it exceeds this many bytes "
   "(atomic rename to `events.jsonl.1`, then an `events_rotated` "
   "event opens the fresh log); unset/<=0 = unbounded (the default)")
_g("JEPSEN_TPU_COSTDB", "bool", False,
   "set: the device cost observatory — capture each executable's XLA "
   "`cost_analysis()`/`memory_analysis()` once per compile, join it "
   "with the measured per-dispatch device windows, publish the "
   "residency gauges, append one record per (executable, geometry) "
   "to `<store>/costdb.jsonl` at sweep end, and add the device "
   "roofline section to `--report`; off (the default) writes zero "
   "new files and costs <1µs per dispatch")
_g("JEPSEN_TPU_RESIDENCY_INTERVAL_S", "float", 5.0,
   "minimum seconds between `device.memory_stats()` polls for the "
   "`hbm_device_bytes` residency gauge (the cheap gauges still "
   "publish per dispatch); `<=0` disables the poll; only read when "
   "`JEPSEN_TPU_COSTDB` is on")
_g("JEPSEN_TPU_KERNEL_STATS", "bool", False,
   "set: kernel search telemetry — checker dispatches additionally "
   "return a per-history graph/search stats vector (edge counts, "
   "closure rounds, SCC shape, decision-boundary margin; WGL "
   "frontier/backtrack counters), journaled to "
   "`<store>/analytics.jsonl` and aggregated into the report's "
   "\"search\" section; off (the default) leaves verdicts, files and "
   "executables byte-identical at <1µs per dispatch")
_g("JEPSEN_TPU_KERNEL_STATS_SAMPLE", "int", 1,
   "journal every Nth history's stats line into `analytics.jsonl` "
   "(in-memory aggregates and the report still cover every history); "
   "`1` (the default) journals all; only read when "
   "`JEPSEN_TPU_KERNEL_STATS` is on")
# -- kernels / backend ------------------------------------------------------
_g("JEPSEN_TPU_BACKEND", "str", None,
   "analysis backend override: `tpu`|`cpu`|`race` (the CLI's "
   "`--backend` exports it; `auto` resolves by hardware)")
_g("JEPSEN_TPU_PLATFORM", "str", None,
   "pin the jax platform set (e.g. `cpu`, `tpu`, `axon,cpu`) before "
   "backend init; also selects the real-hardware test tier")
_g("JEPSEN_TPU_CLOSURE", "str", "",
   "closure formulation: `bf16`|`int8`|`pallas`|`pallas-int8` "
   "(auto default is the XLA int8 matmul pipeline)",
   choices=("", "bf16", "int8", "pallas", "pallas-int8"))
_g("JEPSEN_TPU_FUSED_CLASSIFY", "bool", True,
   "`0`: detect-then-classify two-pass instead of the fused kernel")
_g("JEPSEN_TPU_FRONTIER", "int", 512,
   "bounded-frontier arena size for the sorted-frontier register "
   "kernel")
_g("JEPSEN_TPU_PROBE_TIMEOUT", "float", 120.0,
   "seconds the bounded subprocess backend probe may take before the "
   "platform is declared unreachable")
# -- ingest / native --------------------------------------------------------
_g("JEPSEN_TPU_NATIVE_INGEST", "bool", True,
   "`0`: Python jsonl→tensor encoder")
_g("JEPSEN_TPU_NATIVE_SPLIT", "bool", True,
   "`0`: Python per-key splitter for register sweeps")
_g("JEPSEN_TPU_NO_NATIVE", "bool", False,
   "set (non-`0`): disable every ctypes-loaded helper")
_g("JEPSEN_TPU_NATIVE_LIB_DIR", "str", None,
   "load the native `.so`s from this directory instead of "
   "building into `native/build/` — no rebuild, no silent fallback "
   "to a production lib (`make native-sanitize` points it at the "
   "ASan/UBSan instrumented builds)")
_g("JEPSEN_TPU_SHM_INGEST", "bool", True,
   "`0`: pool-encoded histories ride the classic pickle pipe instead "
   "of `multiprocessing.shared_memory` descriptors (also "
   "auto-falls-back when /dev/shm is unusable)")
_g("JEPSEN_TPU_PIPELINE", "bool", False,
   "set: force the multi-process ingest pipeline even on single-core "
   "hosts")
_g("JEPSEN_TPU_ENCODE_CACHE", "bool", True,
   "`0`: no `encoded.v1.bin` sidecar reads or writes — every sweep "
   "re-parses")
_g("JEPSEN_TPU_ENCODE_CACHE_WRITE", "bool", True,
   "`0`: read-only cache (hit existing sidecars, never write — e.g. "
   "a read-only store mount)")
_g("JEPSEN_TPU_PACK_THREAD", "bool", True,
   "`0`: bucket packing + `device_put` stay inline on the "
   "dispatching thread instead of the dedicated pack-h2d thread")
# -- warm path --------------------------------------------------------------
_g("JEPSEN_TPU_SIDECAR_V2", "bool", True,
   "`0`: write/read only v1 (unpadded) encoded sidecars — no "
   "dispatch-shaped `encoded.v2.bin`, no v1→v2 upgrade, warm sweeps "
   "pack with host copies as before")
_g("JEPSEN_TPU_DONATE_BUFFERS", "bool", True,
   "`0`: single-device bucket dispatches keep their input buffers "
   "instead of donating them to XLA (`donate_argnums`) for reuse "
   "across dispatches")
_g("JEPSEN_TPU_AOT_CACHE", "bool", True,
   "`0`: no persistent AOT executable cache — every process pays its "
   "own XLA compiles (the in-memory jit cache still applies)")
_g("JEPSEN_TPU_COMPILE_CACHE_DIR", "str", None,
   "directory for the persistent AOT executable cache (default "
   "`~/.cache/jepsen_tpu/executables`)")
# -- multi-host mesh --------------------------------------------------------
_g("JEPSEN_TPU_MESH", "bool", False,
   "set: `analyze-store` runs as ONE SHARD of a multi-host mesh sweep "
   "(the `--mesh` flag exports it): deterministic shard of the run "
   "dirs, per-shard `verdicts-<shard>.jsonl` journal and "
   "`trace-shard<k>.json` artifacts, coordinator merge on shard 0")
_g("JEPSEN_TPU_MESH_SHARD", "int", None,
   "mesh shard index override (re-assign a dead host's shard to "
   "another host); default: `jax.process_index()` on a distributed "
   "job, else 0")
_g("JEPSEN_TPU_MESH_SHARDS", "int", None,
   "mesh shard-count override — set on every host to shard a store "
   "WITHOUT a jax.distributed coordinator; default: "
   "`jax.process_count()` on a distributed job, else 1")
_g("JEPSEN_TPU_MESH_WAIT_S", "float", 600.0,
   "seconds the mesh coordinator (shard 0) waits for the other "
   "shards' done markers before declaring them lost (re-assignable, "
   "exit code ≥2) and merging what exists; `0` merges immediately")
# -- verdict service --------------------------------------------------------
_g("JEPSEN_TPU_SERVE_SOCKET", "str", None,
   "unix-socket path the `serve` verdict daemon listens on (default "
   "`<store>/serve.sock`); tenants stream length-prefixed frames over "
   "it and get verdicts back")
_g("JEPSEN_TPU_SERVE_PORT", "int", None,
   "TCP port for the `serve` daemon instead of the unix socket "
   "(`0` binds an ephemeral port, printed in the ready line); unset = "
   "unix socket")
_g("JEPSEN_TPU_SERVE_MAX_QUEUE", "int", 256,
   "per-tenant admission-queue depth of the `serve` daemon; a CHECK "
   "past the cap gets an explicit `retry-after` frame (never a "
   "silent drop)")
_g("JEPSEN_TPU_SERVE_WEIGHTS", "str", "",
   "per-tenant fairness weights for the `serve` daemon's continuous "
   "batcher, e.g. `fleetA=3,fleetB=1` (unlisted tenants weigh 1); "
   "fold shares follow weighted deficit round-robin")
_g("JEPSEN_TPU_SERVE_DRAIN_S", "float", 30.0,
   "seconds the `serve` daemon spends draining admitted work on "
   "SIGTERM before closing; work never admitted (or past the "
   "deadline) is left for the tenant to resend — never half-acked")
_g("JEPSEN_TPU_SERVE_RETRY_S", "float", 60.0,
   "client-side retry budget: `ServeClient` stops retrying a "
   "backpressured or unreachable endpoint this many seconds after "
   "its last progress (verdict or successful send) and raises "
   "`ServeUnavailable` — the terminal error fleet failover bounds "
   "tenants to; `0` fails on the first retryable condition")
# -- serve fleet ------------------------------------------------------------
_g("JEPSEN_TPU_FLEET_HEARTBEAT_S", "float", 1.0,
   "seconds between a fleet daemon's beacon rewrites "
   "(`fleet-d<k>.json`: pid, epoch, load) — the router's liveness "
   "evidence; lower = faster death detection, more beacon churn")
_g("JEPSEN_TPU_FLEET_FAILOVER_S", "float", 5.0,
   "beacon staleness (kernel mtime age, immune to daemon clock skew) "
   "past which the fleet router declares a daemon dead, fences it "
   "out of the membership epoch, and replays its tenants' journals "
   "on a successor")
_g("JEPSEN_TPU_FLEET_SPILL_DEPTH", "int", 32,
   "queued histories on a tenant's affine daemon past which the "
   "fleet router spills new checks to the least-loaded live daemon "
   "(by beacon queue depth, tie-broken on modeled HBM bytes) "
   "instead of queueing deeper")
# -- cost-aware planner -----------------------------------------------------
_g("JEPSEN_TPU_PLANNER", "bool", False,
   "set: the cost-aware dispatch planner — route per-history tier "
   "(python/native/TPU split + dispatch), bucket geometry and "
   "fused-vs-two-pass choice, and price `serve` admission, from a "
   "cost model fit on `costdb.jsonl` × `analytics.jsonl` (persisted "
   "as `<store>/plan.json`); cold start (no costdb, unseen device "
   "kind, corrupt plan) degrades to the exact current heuristics — "
   "planner decisions never change verdicts, only placement")
_g("JEPSEN_TPU_PLANNER_PATH", "str", None,
   "explicit `plan.json` path for the planner (load AND save), e.g. "
   "one shared model across stores or a daemon fleet; default "
   "`<store>/plan.json`; only read when `JEPSEN_TPU_PLANNER` is on")
# -- robustness -------------------------------------------------------------
_g("JEPSEN_TPU_STRICT", "bool", False,
   "set: restore fail-fast — no quarantine, no OOM backdown; the "
   "first failure raises (CI bisection, debugging one corrupt store)")
_g("JEPSEN_TPU_DISPATCH_TIMEOUT_S", "float", None,
   "per-dispatch device watchdog: bound each `block_until_ready` to "
   "this many seconds, retry once, then quarantine the bucket")
_g("JEPSEN_TPU_FAULT_INJECT", "str", "",
   "self-nemesis spec, e.g. `encode:0.05,oom:first` — deterministic "
   "encode faults / worker kills / simulated OOMs (see Robustness)")
# -- protocol markers (not env vars) ----------------------------------------
_g("JEPSEN_TPU_EC", "marker", "__JEPSEN_TPU_EC:",
   "ssh exit-code marker string the control layer echoes from remote "
   "shells to disambiguate ssh's own 255 from the command's — a "
   "protocol constant, not an env var")


# ---------------------------------------------------------------------------
# Accessors — the only sanctioned JEPSEN_TPU_* env reads/writes.
# ---------------------------------------------------------------------------

def gate(name: str) -> Gate:
    """The declaration for `name` (KeyError on an unregistered gate —
    reads of undeclared names must fail loudly, not invent a gate)."""
    return GATES[name]


def get(name: str):
    """The typed value of gate `name` from the current environment."""
    g = GATES[name]
    if g.kind == "marker":
        return g.default
    return g.parse(os.environ.get(name))


def get_raw(name: str) -> str | None:
    """The raw env string of a REGISTERED gate (None = unset) — for
    the rare caller that needs the spelling, not the parse (e.g. the
    fault injector keying its state on the exact spec string)."""
    GATES[name]  # KeyError on unregistered names
    return os.environ.get(name)


def is_set(name: str) -> bool:
    """Is the gate explicitly set (non-empty) in the environment?"""
    GATES[name]
    return bool(os.environ.get(name))


def export(name: str, value) -> None:
    """Write gate `name` into the environment (the CLI flag→env
    export; subprocesses and embedded callers then see the choice).
    Booleans serialize to the canonical `1`/`0`."""
    g = GATES[name]
    assert g.kind != "marker", f"{name} is a protocol marker, not a gate"
    if isinstance(value, bool):
        value = "1" if value else "0"
    os.environ[name] = str(value)


def unset(name: str) -> None:
    """Remove gate `name` from the environment."""
    GATES[name]
    os.environ.pop(name, None)


# ---------------------------------------------------------------------------
# README rendering — the env-gate table is generated, never hand-kept.
# ---------------------------------------------------------------------------

#: Markers delimiting the generated block in README.md; lint rule
#: JT-GATE-003 fails when the committed block drifts from the registry.
TABLE_BEGIN = "<!-- env-gates:begin (generated by jepsen_tpu.gates) -->"
TABLE_END = "<!-- env-gates:end -->"


def render_env_table() -> str:
    """The README env-gate table, one row per registered gate. Literal
    `|` in a doc line is escaped: markdown splits cells on every
    unescaped pipe, code spans included."""
    lines = ["| gate | default | meaning |", "|---|---|---|"]
    for g in GATES.values():
        doc = g.doc.replace("|", "\\|")
        lines.append(f"| `{g.name}` | {g.default_str()} | {doc} |")
    return "\n".join(lines)


def render_env_block() -> str:
    """The full generated README block, markers included."""
    return f"{TABLE_BEGIN}\n{render_env_table()}\n{TABLE_END}"
