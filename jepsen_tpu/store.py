"""Persistence: the on-disk store of test runs.

Layout mirrors the reference (jepsen/src/jepsen/store.clj:29,118-140):

    store/<test-name>/<start-time>/
        history.edn     one op map per line (reference-compatible)
        history.jsonl   same ops as JSON lines (fast native load path)
        test.json       the serializable test map
        results.edn     checker verdict (reference-compatible)
        results.json    same verdict as JSON
        jepsen.log      run log
        ...             checker artifacts (plots, timelines)

plus `current`/`latest` symlinks at both the store root and the test dir
(store.clj:307-333). `save_1` persists the test+history before analysis so a
crash during checking never loses data (core.clj:630); `save_2` adds results
(store.clj:385-397).
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import shutil
from pathlib import Path
from typing import Any, Iterable

from . import edn, history as h
from .util import chunk_vec, real_pmap

log = logging.getLogger(__name__)

# Keys that never serialize (functions, live connections...).
# Reference: store.clj:160-168.
NONSERIALIZABLE_KEYS = (
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "remote", "store", "logging", "barrier", "sessions", "args",
)

DEFAULT_BASE = "store"

# History chunks are written in parallel above this size
# (reference util.clj:208: threshold 16,384 ops).
PARALLEL_WRITE_THRESHOLD = 16384


def _stringify(v: Any) -> Any:
    """Best-effort conversion of a test-map value to JSON-compatible data."""
    if isinstance(v, dict):
        return {str(k): _stringify(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_stringify(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted((_stringify(x) for x in v), key=repr)
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, datetime.datetime):
        return v.isoformat()
    return repr(v)


def load_history_dir(run_dir: str | os.PathLike) -> list[h.Op]:
    """History ops from a run dir: history.jsonl preferred,
    reference-format history.edn fallback. Module-level (not a Store
    method) so encode-only worker processes can load runs without
    constructing a store."""
    d = Path(run_dir)
    jl = d / "history.jsonl"
    if jl.exists():
        # one json.loads over a joined array is ~2.3x faster than a
        # loads per line — ingest parse is the dominant host cost of
        # big store sweeps
        lines = [ln for ln in jl.read_text().splitlines() if ln.strip()]
        if not lines:
            return []
        return json.loads("[" + ",".join(lines) + "]")
    ed = d / "history.edn"
    if ed.exists():
        return h.history_from_edn(ed.read_text())
    raise FileNotFoundError(f"no history in {d}")


class Store:
    """A store rooted at `base` (default ./store)."""

    def __init__(self, base: str | os.PathLike = DEFAULT_BASE):
        self.base = Path(base)

    # -- paths ------------------------------------------------------------

    def test_dir(self, test: dict) -> Path:
        name = test.get("name", "noname")
        start = test.get("start-time")
        if start is None:
            start = datetime.datetime.now().strftime("%Y%m%dT%H%M%S.%f")[:-3]
            test["start-time"] = start
        return self.base / name / str(start)

    def path(self, test: dict, *parts: str) -> Path:
        p = self.test_dir(test).joinpath(*parts)
        p.parent.mkdir(parents=True, exist_ok=True)
        return p

    # -- symlinks (store.clj:307-333) --------------------------------------

    def _relink(self, link: Path, target: Path) -> None:
        link.parent.mkdir(parents=True, exist_ok=True)
        if link.is_symlink() or link.exists():
            # Only move forward: re-analyzing an OLD run (analyze-store's
            # sweep) must not steal latest/current from a newer run.
            try:
                if link.resolve().name > target.name:
                    return
            except OSError:
                pass
            link.unlink()
        link.symlink_to(os.path.relpath(target, link.parent))

    def update_symlinks(self, test: dict) -> None:
        d = self.test_dir(test)
        self._relink(d.parent / "latest", d)
        self._relink(self.base / "latest", d)
        self._relink(self.base / "current", d)

    # -- writes -----------------------------------------------------------

    def write_history(self, test: dict) -> None:
        hist = test.get("history", [])
        d = self.test_dir(test)
        d.mkdir(parents=True, exist_ok=True)
        if len(hist) > PARALLEL_WRITE_THRESHOLD:
            chunks = chunk_vec(PARALLEL_WRITE_THRESHOLD, hist)
            parts = real_pmap(
                lambda c: (h.history_to_edn(c),
                           "".join(json.dumps(_stringify(o)) + "\n" for o in c)),
                chunks)
            with open(d / "history.edn", "w") as fe, \
                 open(d / "history.jsonl", "w") as fj:
                for e_part, j_part in parts:
                    fe.write(e_part)
                    fj.write(j_part)
        else:
            (d / "history.edn").write_text(h.history_to_edn(hist) if hist else "")
            (d / "history.jsonl").write_text(
                "".join(json.dumps(_stringify(o)) + "\n" for o in hist))

    def write_test(self, test: dict) -> None:
        t = {k: _stringify(v) for k, v in test.items()
             if k not in NONSERIALIZABLE_KEYS and k not in ("history", "results")}
        p = self.path(test, "test.json")
        p.write_text(json.dumps(t, indent=2, default=repr))

    def write_results(self, test: dict) -> None:
        res = test.get("results", {})
        self.path(test, "results.json").write_text(
            json.dumps(_stringify(res), indent=2, default=repr))
        self.path(test, "results.edn").write_text(
            edn.dumps(_results_to_edn(res)) + "\n")

    def save_1(self, test: dict) -> dict:
        """Persist test + history (before analysis)."""
        self.write_test(test)
        self.write_history(test)
        self.update_symlinks(test)
        return test

    def write_trace(self, test: dict) -> Path | None:
        """Persist the current run tracer's `trace.json` (Chrome
        trace-event format, Perfetto-loadable) and `metrics.json` next
        to history.edn — every run self-attributes, not just benches.
        No-op (returns None) when tracing is disabled
        (JEPSEN_TPU_TRACE=0 / --no-trace), or when the current tracer
        is sweep-scoped (analyze-store fallbacks re-analyze runs under
        the SWEEP's tracer; exporting it here would write the whole
        sweep's events into each run dir, once per run)."""
        from . import trace
        t = trace.get_current()
        if not getattr(t, "enabled", False) \
                or getattr(t, "scope", "run") != "run":
            return None
        d = self.test_dir(test)
        d.mkdir(parents=True, exist_ok=True)
        p = t.export(d / "trace.json")
        t.export_metrics(d / "metrics.json")
        return p

    def save_2(self, test: dict) -> dict:
        """Persist results (after analysis), plus the run's trace +
        metrics artifacts (observability must never sink persistence,
        so trace export failures degrade to a warning)."""
        self.write_test(test)
        self.write_results(test)
        try:
            self.write_trace(test)
        except Exception:
            log.warning("trace/metrics export failed", exc_info=True)
        self.update_symlinks(test)
        return test

    # -- reads ------------------------------------------------------------

    def tests(self) -> dict[str, dict[str, Path]]:
        """Map of test-name -> {start-time -> dir} (store.clj:275)."""
        out: dict[str, dict[str, Path]] = {}
        if not self.base.exists():
            return out
        for name_dir in sorted(self.base.iterdir()):
            if not name_dir.is_dir() or name_dir.name in ("latest", "current"):
                continue
            runs = {d.name: d for d in sorted(name_dir.iterdir())
                    if d.is_dir() and d.name != "latest"}
            if runs:
                out[name_dir.name] = runs
        return out

    def iter_run_dirs(self, name: str | None = None,
                      shard: int | None = None, n_shards: int = 1):
        """Lazy, shard-assignable store walk: yields run dirs in the
        same order as `sorted(all_run_dirs())` without materializing
        the whole store's Path list up front — one `os.scandir` per
        test-name directory (dirent type answers is_dir for real
        dirs; only symlinked entries pay a stat), so directory
        listing doesn't dominate at 10^6 run dirs (ROADMAP item 5's
        walk side). The `latest`/`current` links are skipped by NAME,
        exactly like the legacy tests() walk — other symlinked dirs
        (a store assembled by linking runs from another volume) are
        followed as before. With `shard`/`n_shards` only the dirs
        whose `shard_of` key lands on `shard` are yielded — the mesh
        sweep's deterministic partition: every host derives the SAME
        split from nothing but the store listing, no coordinator
        round trip."""
        base = self.base
        try:
            with os.scandir(base) as it:
                names = sorted(
                    e.name for e in it
                    if e.name not in ("latest", "current")
                    and e.is_dir())
        except OSError:
            return
        for nm in names:
            if name is not None and nm != name:
                continue
            try:
                with os.scandir(base / nm) as it:
                    runs = sorted(
                        e.name for e in it
                        if e.name != "latest" and e.is_dir())
            except OSError:
                continue
            for rn in runs:
                if shard is not None \
                        and shard_of(f"{nm}/{rn}", n_shards) != shard:
                    continue
                yield base / nm / rn

    def all_run_dirs(self) -> list[Path]:
        return list(self.iter_run_dirs())

    def latest(self) -> Path | None:
        link = self.base / "latest"
        if link.exists():
            return link.resolve()
        dirs = self.all_run_dirs()
        # Most recent start-time across all test names.
        return max(dirs, key=lambda d: d.name) if dirs else None

    def load_history(self, run_dir: str | os.PathLike) -> list[h.Op]:
        """Load a history from a run dir: prefers history.jsonl, falls back
        to reference-format history.edn."""
        return load_history_dir(run_dir)

    def load_test(self, run_dir: str | os.PathLike) -> dict:
        """Load a run dir — ours (test.json) or the reference's
        (test.fressian, store.clj:372-383)."""
        # Resolve symlinks (latest/current) so the dir name below is the
        # real timestamp, not "latest" — re-linking against the link name
        # would create a self-loop.
        d = Path(run_dir).resolve()
        test: dict = {}
        tj = d / "test.json"
        tf = d / "test.fressian"
        if tj.exists():
            test = json.loads(tj.read_text())
        elif tf.exists():
            from . import fressian
            raw = fressian.load_test(tf)
            if isinstance(raw, dict):
                # Map keys are edn.Keyword, which subclasses str and
                # equals its bare name — str() normalizes them.
                test = {str(k): v for k, v in raw.items()}
        # The run dir is authoritative for name/start-time so re-analysis
        # writes back into the SAME dir (cli.clj analyze, :381-411),
        # whatever form the serialized test map stored them in.
        test["start-time"] = d.name
        test.setdefault("name", d.parent.name)
        test["history"] = self.load_history(d)
        rj = d / "results.json"
        if rj.exists():
            try:
                test["results"] = json.loads(rj.read_text())
            except (OSError, json.JSONDecodeError):
                # results are a derived artifact: a write truncated by
                # a crash must not make the run unloadable (re-analysis
                # regenerates it)
                pass
        return test

    def load_results(self, run_dir: str | os.PathLike) -> dict | None:
        d = Path(run_dir)
        rj = d / "results.json"
        if rj.exists():
            try:
                return json.loads(rj.read_text())
            except (OSError, json.JSONDecodeError):
                pass
        re_ = d / "results.edn"
        if re_.exists():
            v = edn.loads(re_.read_text())
            return v if isinstance(v, dict) else None
        return None

    def delete(self, name: str | None = None) -> None:
        """Delete a test's runs (or the whole store)."""
        target = self.base / name if name else self.base
        if target.exists():
            shutil.rmtree(target)


# ---------------------------------------------------------------------------
# Resumable verdict journal: verdicts.jsonl at the store root.
#
# An interrupted store sweep must restart where it died, not from
# zero. Each verdict (including quarantined "unknown"s) appends one
# JSON line — {"dir": <run dir relative to the store>, "checker",
# "valid?", plus "quarantined"/"error" when the supervisor abandoned
# the run} — flushed as it lands, so the journal survives SIGKILL of
# the sweep mid-flight. `analyze-store --resume` loads it and skips
# every journaled (dir, checker) pair, counting the recorded validity
# toward the exit code. A line truncated by the crash is skipped on
# load (that run simply re-checks). The journal complements the
# per-run `.sweep-<checker>` sidecars: one O(1) append-only file to
# scan instead of a stat per run dir, and it captures stored-fallback
# and quarantined runs that may write nothing into their run dir.
# ---------------------------------------------------------------------------

class VerdictJournal:
    """Append-only per-history verdict log for one store. Writes are
    best-effort (a read-only store mount must not sink the sweep) and
    line-buffered+flushed so a killed sweep loses at most the line in
    flight."""

    def __init__(self, path: str | os.PathLike,
                 base: str | os.PathLike | None = None):
        self.path = Path(path)
        self.base = Path(base) if base is not None else None
        self._f = None

    def rel(self, run_dir) -> str:
        """The journal's key for a run dir: relative to the store base
        when one is set, so the journal survives the store moving (or
        being swept from a different cwd)."""
        if self.base is not None:
            try:
                return os.path.relpath(run_dir, self.base)
            except ValueError:
                pass
        return str(run_dir)

    def record(self, run_dir, checker: str, res: dict,
               full: bool = False) -> bool:
        """Append one verdict line; returns True when the line landed
        (False = best-effort write failed, e.g. a read-only store —
        the serve daemon flags acks whose journal append failed, since
        those verdicts will be re-checked instead of replayed after a
        restart). With `full=True` the WHOLE result dict rides the
        entry (`"result"`) — the serve daemon's replay contract: a
        reconnecting tenant must get back byte-identical verdicts from
        the journal alone, not a lossy summary. Sweep journals stay
        lean (the run dir's results.json is their full record)."""
        entry = {"dir": self.rel(run_dir), "checker": checker,
                 "valid?": res.get("valid?")}
        for k in ("quarantined", "error"):
            if k in res:
                entry[k] = res[k]
        if full:
            entry["result"] = res
        try:
            if self._f is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                self._f = open(self.path, "a")
                # seal a crash-torn tail: a journal killed mid-write can
                # end without its newline, and appending straight after
                # those bytes would corrupt THIS record too (load skips
                # the merged line — one verdict silently lost to resume)
                if self._f.tell() > 0:
                    with open(self.path, "rb") as rf:
                        rf.seek(-1, os.SEEK_END)
                        torn = rf.read(1) != b"\n"
                    if torn:
                        self._f.write("\n")
                        from .obs import events as obs_events
                        obs_events.emit("journal_seal",
                                        path=str(self.path))
            self._f.write(json.dumps(entry) + "\n")
            self._f.flush()
            return True
        except (OSError, TypeError, ValueError):
            # OSError: read-only store; TypeError/ValueError: a full=
            # result that isn't JSON-able — either way best-effort
            log.debug("verdict journal append failed for %s",
                      self.path, exc_info=True)
            return False

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    @staticmethod
    def load(path: str | os.PathLike) -> dict[tuple[str, str], dict]:
        """{(dir, checker): last entry} from an existing journal;
        unparseable lines (the crash-truncated tail) are skipped."""
        out: dict[tuple[str, str], dict] = {}
        p = Path(path)
        if not p.is_file():
            return out
        try:
            lines = p.read_text().splitlines()
        except OSError:
            return out
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                e = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(e, dict) and "dir" in e and "checker" in e:
                out[(str(e["dir"]), str(e["checker"]))] = e
        return out


# ---------------------------------------------------------------------------
# The persistent cost database: costdb.jsonl at the store root.
#
# The device cost observatory (jepsen_tpu/obs/device.py, behind
# JEPSEN_TPU_COSTDB) captures one record per (compiled executable,
# bucket geometry) — XLA cost/memory analyses joined with the measured
# dispatch windows — and appends them here at sweep end: one flushed
# JSON line each, the VerdictJournal discipline, so a torn tail from a
# killed flush is skipped on load instead of poisoning the reader.
# Mesh shards write `costdb-shard<k>.jsonl`; the coordinator merges
# them (obs.device.merge_records) into one deduplicated costdb.jsonl.
# The file is the training data ROADMAP item 4's cost-aware planner
# consumes — an append-only empirical cost model, not a cache (repeat
# sweeps append fresh records; consumers dedup by record key).
# ---------------------------------------------------------------------------

COSTDB_NAME = "costdb.jsonl"


def costdb_path(store_base, shard: int | None = None) -> Path:
    """The costdb for a store — per-shard under a mesh sweep, so two
    hosts never interleave appends in one file."""
    if shard is None:
        return Path(store_base) / COSTDB_NAME
    return Path(store_base) / f"costdb-shard{shard}.jsonl"


def append_costdb(path, records: list[dict]) -> int:
    """Append records as JSON lines, each flushed as written; a
    crash-torn tail from a previous writer is sealed first (the
    journal's rule — appending after a line that lost its newline
    would merge two records into one unparseable line). Best-effort:
    a read-only store returns 0, never raises."""
    p = Path(path)
    n = 0
    try:
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "a") as f:
            if f.tell() > 0:
                with open(p, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        f.write("\n")
            for rec in records:
                try:
                    line = json.dumps(rec)
                except (TypeError, ValueError):
                    continue
                f.write(line + "\n")
                f.flush()
                n += 1
    except OSError:
        log.debug("costdb append failed for %s", p, exc_info=True)
    return n


class CostTable(list):
    """The typed costdb read result: the record dicts plus the
    provenance every consumer was re-deriving by hand — which path was
    read and whether that file existed at all. Subclasses `list`, so
    every existing consumer (iteration, truthiness, the mesh merge's
    `any(lists)`) keeps working unchanged: a missing or empty shard
    reads as a falsy table, never an exception or a sentinel the
    caller must special-case."""

    __slots__ = ("path", "exists")

    def __init__(self, records=(), *, path=None, exists: bool = False):
        super().__init__(records)
        self.path = Path(path) if path is not None else None
        self.exists = bool(exists)

    @property
    def empty(self) -> bool:
        """No records — the planner's cold-start predicate (an absent
        file and a present-but-recordless one both count)."""
        return not self


def load_costdb(path) -> CostTable:
    """Records from a costdb as a `CostTable`, in file order;
    unparseable lines (the crash-torn tail) are skipped, mirroring
    VerdictJournal.load. A missing or unreadable file returns a typed
    EMPTY table (`exists=False`) instead of making every consumer
    re-implement the existence check."""
    out: list[dict] = []
    p = Path(path)
    if p.is_dir():
        p = p / COSTDB_NAME
    if not p.is_file():
        return CostTable(path=p, exists=False)
    try:
        lines = p.read_text().splitlines()
    except OSError:
        return CostTable(path=p, exists=False)
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "geometry" in rec:
            out.append(rec)
    return CostTable(out, path=p, exists=True)


# ---------------------------------------------------------------------------
# The fitted dispatch plan: plan.json at the store root — the
# cost-aware planner's model snapshot (JEPSEN_TPU_PLANNER,
# jepsen_tpu/planner.py). Published whole via temp + os.replace
# (snapshot protocol, declared in lint/contracts.py STORE_ARTIFACTS)
# so warm sweeps and the serve daemon load the fit instead of
# re-deriving it from the costdb every start.
# ---------------------------------------------------------------------------

PLAN_NAME = "plan.json"


def plan_path(store_base) -> Path:
    """The planner's fitted-model snapshot for a store.
    `JEPSEN_TPU_PLANNER_PATH` overrides — one shared plan across
    stores or a daemon fleet loads (and saves) there instead."""
    from . import gates
    override = gates.get("JEPSEN_TPU_PLANNER_PATH")
    if override:
        return Path(override)
    return Path(store_base) / PLAN_NAME


# ---------------------------------------------------------------------------
# The kernel search-telemetry ledger: analytics.jsonl at the store
# root (JEPSEN_TPU_KERNEL_STATS, jepsen_tpu/obs/search.py). One JSON
# line per checked history — the per-relation edge counts, closure
# rounds, SCC shape and decision-boundary margin the checker kernels
# now emit beside the verdict — flushed as written with the costdb's
# torn-tail discipline. Mesh shards write `analytics-shard<k>.jsonl`;
# the coordinator folds them into one analytics.jsonl. The ledger is
# the seed corpus for the adversarial near-miss search (ROADMAP item
# 3) and, joined with the costdb, the planner's empirical complexity
# model (item 4).
# ---------------------------------------------------------------------------

ANALYTICS_NAME = "analytics.jsonl"


def analytics_path(store_base, shard: int | None = None) -> Path:
    """The analytics ledger for a store — per-shard under a mesh
    sweep, so two hosts never interleave appends in one file."""
    if shard is None:
        return Path(store_base) / ANALYTICS_NAME
    return Path(store_base) / f"analytics-shard{shard}.jsonl"


def append_analytics(path, records: list[dict]) -> int:
    """Append stats records as JSON lines, each flushed as written; a
    crash-torn tail from a previous writer is sealed first (the
    journal's rule). Best-effort: a read-only store returns 0, never
    raises.

    Deliberately mirrors append_costdb rather than sharing a helper:
    the JT-DUR prover attributes append-handle flush discipline to
    the REGISTRY-DECLARED writer qualname, and hoisting the open/
    write/flush loop into a path-parameterized helper would take
    these exact lines out of static proof — keep the twins in sync
    by hand (they are also crash-sim tested independently)."""
    p = Path(path)
    n = 0
    try:
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "a") as f:
            if f.tell() > 0:
                with open(p, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        f.write("\n")
            for rec in records:
                try:
                    line = json.dumps(rec)
                except (TypeError, ValueError):
                    continue
                f.write(line + "\n")
                f.flush()
                n += 1
    except OSError:
        log.debug("analytics append failed for %s", p, exc_info=True)
    return n


def load_analytics(path) -> list[dict]:
    """Records from an existing analytics ledger, in file order;
    unparseable lines (the crash-torn tail) are skipped, mirroring
    VerdictJournal.load."""
    out: list[dict] = []
    p = Path(path)
    if p.is_dir():
        p = p / ANALYTICS_NAME
    if not p.is_file():
        return out
    try:
        lines = p.read_text().splitlines()
    except OSError:
        return out
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "checker" in rec:
            out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Verdict-service artifacts: the `jepsen-tpu serve` daemon's on-disk
# surface, all at the store root (the flat per-shard convention of
# verdicts-<k>.jsonl / costdb-shard<k>.jsonl):
#
#   serve.sock                     the tenant socket (unix mode)
#   serve.pid                      the daemon's pidfile (atomic marker)
#   serve-<tenant>.verdicts.jsonl  per-tenant verdict journal — one
#                                  FULL verdict per line (the replay
#                                  record), VerdictJournal discipline
#   serve-requests.jsonl           admitted-request spool (triage for
#                                  a crashed daemon; cleared at start)
#
# Every path is built here (and declared in lint/contracts.py
# STORE_ARTIFACTS) so the JT-DUR durability prover covers the daemon
# the way it covers sweeps.
# ---------------------------------------------------------------------------

def safe_tenant(name: str) -> str:
    """A tenant id as a filesystem-safe slug: the journal path embeds
    it, and a tenant must not be able to name itself `../../etc` (or
    collide with another tenant after mangling — hence the hash
    suffix whenever anything was replaced). Dots are mangled too, so
    no `..` survives in any form."""
    cleaned = "".join(c if c.isalnum() or c in "-_" else "_"
                      for c in str(name))[:64] or "tenant"
    if cleaned != str(name):
        h = _buf_xxh64(str(name).encode()) & 0xffffffff
        cleaned = f"{cleaned}-{h:08x}"
    return cleaned


def serve_socket_path(store_base) -> Path:
    """The daemon's unix socket (JEPSEN_TPU_SERVE_SOCKET overrides)."""
    return Path(store_base) / "serve.sock"


def serve_pid_path(store_base) -> Path:
    return Path(store_base) / "serve.pid"


def tenant_journal_path(store_base, tenant: str) -> Path:
    """One tenant's verdict journal — the daemon's crash evidence AND
    the tenant's resume evidence (reconnect replays from it without
    re-checking)."""
    return Path(store_base) / f"serve-{safe_tenant(tenant)}.verdicts.jsonl"


def request_spool_path(store_base) -> Path:
    """The admitted-request spool: one line per admission, so a
    post-mortem on a killed daemon can tell admitted-but-unverdicted
    work (resent by tenants) from never-admitted work."""
    return Path(store_base) / "serve-requests.jsonl"


# ---------------------------------------------------------------------------
# Fleet artifacts: the `jepsen-tpu fleet` router's on-disk surface,
# also flat at the store root. All N daemons share ONE store (so a
# successor can replay a dead peer's per-tenant journals directly):
#
#   fleet.sock            the router's tenant-facing socket
#   fleet-d<k>.sock       daemon k's upstream socket (router-facing)
#   fleet-d<k>.json       daemon k's beacon — pid/epoch/load, atomically
#                         replaced every heartbeat; the router reads
#                         LIVENESS off the kernel mtime (clock-skew
#                         immune) and LOAD off the payload
#   fleet-epoch.json      the membership epoch marker (the fence): the
#                         router bumps it before reassigning a dead
#                         daemon's tenants; a zombie checks it before
#                         journaling and drops fenced folds
#   fleet-reassign.jsonl  the reassignment journal — one line per
#                         (epoch, dead daemon, tenant, successor)
#
# Declared in lint/contracts.py STORE_ARTIFACTS like the rest.
# ---------------------------------------------------------------------------

def fleet_socket_path(store_base) -> Path:
    """The fleet router's tenant-facing unix socket."""
    return Path(store_base) / "fleet.sock"


def fleet_daemon_socket_path(store_base, instance: int) -> Path:
    """Fleet daemon `instance`'s own listening socket (the router
    proxies tenant frames to it here)."""
    return Path(store_base) / f"fleet-d{int(instance)}.sock"


def fleet_member_path(store_base, instance: int) -> Path:
    """Fleet daemon `instance`'s beacon file (atomically replaced
    every JEPSEN_TPU_FLEET_HEARTBEAT_S)."""
    return Path(store_base) / f"fleet-d{int(instance)}.json"


def fleet_epoch_path(store_base) -> Path:
    """The fleet membership epoch marker — the zombie fence."""
    return Path(store_base) / "fleet-epoch.json"


def fleet_reassign_path(store_base) -> Path:
    """The router's tenant-reassignment journal (failover evidence)."""
    return Path(store_base) / "fleet-reassign.jsonl"


# ---------------------------------------------------------------------------
# Persistent encoded cache: encoded.v1.bin / encoded.v2.bin sidecars.
#
# Re-analysis sweeps (analyze-store --resume, repeated benches, CI) pay
# the full history parse every time even though a run dir's history is
# immutable once written. Each successful lean encode therefore leaves
# a flat binary sidecar next to history.jsonl — tensors laid out raw so
# a warm sweep mmaps them back as zero-copy numpy views, skipping
# json/dict parsing entirely. The cache key is the history file's
# (size, mtime_ns, xxh64-over-first+last-64KiB): any byte growth,
# rewrite, or touch invalidates (the sidecar is then ignored and
# overwritten on the next encode). The native encoder
# (native/hist_encode.cc, jt_ha_write_sidecar) writes the SAME layout
# straight from its own buffers, so the C++ fast path never
# round-trips through Python to populate the cache.
#
# v2 (dispatch-shaped, append checker only): the same container, but
# the tensors the batch packer feeds the device are persisted
# PRE-PADDED to the singleton bucket geometry the sweep planner would
# choose (kernels.BatchShape.plan: txn axis to a multiple of 128,
# triple/key axes to 8), with the effective completion keys
# precomputed. A warm sweep whose bucket shape matches can then hand
# the mmap views straight to device_put — no pack_batch, no host
# copies (parallel counts `warm_copy_bytes` to prove it). The lean
# (unpadded) arrays the rest of the package uses are SLICES of the
# padded ones, so v2 costs no second copy on disk either. v1 sidecars
# stay readable and are upgraded to v2 in place on first warm load
# (`sidecar_upgrades` counter + a `cache_rebuild` event); the wr
# checker keeps v1 — its edge-matrix packer has no padded-tensor fast
# path to feed.
# ---------------------------------------------------------------------------

ENCODED_MAGIC = b"JTENC01\n"
ENCODED_MAGIC_V2 = b"JTENC02\n"

#: The dispatch-padding multiples — MUST mirror kernels.BatchShape.plan
#: (txn axis 128 = the MXU tile, everything else 8); parity is pinned
#: by tests/test_warm_path.py so the two can't drift. Kept local so
#: pool workers writing sidecars never import jax.
_PAD_TXNS = 128
_PAD_MINOR = 8


def _pad_up(x: int, multiple: int) -> int:
    """kernels.pad_to, re-stated (round up to a positive multiple)."""
    return max(multiple, ((x + multiple - 1) // multiple) * multiple)


def dispatch_pad_plan(enc) -> dict:
    """The padded geometry a singleton-bucket BatchShape.plan would
    pick for this encoding — the shape the v2 sidecar persists at."""
    return {"n_txns": _pad_up(enc.n, _PAD_TXNS),
            "n_appends": _pad_up(len(enc.appends), _PAD_MINOR),
            "n_reads": _pad_up(len(enc.reads), _PAD_MINOR),
            "n_keys": _pad_up(enc.n_keys, _PAD_MINOR),
            "max_pos": _pad_up(enc.max_pos, _PAD_MINOR)}

# Per-checker array fields of a lean encoding, in canonical layout
# order — the ONE list the shm transport (jepsen_tpu/shm.py) and the
# sidecar writer below both serialize and both rebuild from (the C++
# sidecar writer mirrors it in hist_encode.cc's write_sidecar).
ENCODED_FIELDS = {
    "append": ("appends", "reads", "status", "process",
               "invoke_index", "complete_index"),
    "wr": ("edges", "status", "process", "invoke_index",
           "complete_index"),
}


def encoded_arrays(enc, checker: str) -> list:
    """[(field, contiguous ndarray)] for a lean encoding, in
    ENCODED_FIELDS order (WrEncoded.edges — a list of 3-tuples — is
    densified to int32 [E,3])."""
    import numpy as np
    out = []
    for f in ENCODED_FIELDS[checker]:
        v = getattr(enc, f)
        if f == "edges":
            v = np.asarray(v or np.zeros((0, 3)),
                           np.int32).reshape(-1, 3)
        out.append((f, np.ascontiguousarray(v)))
    return out


def rebuild_encoded(checker: str, arrays: dict, meta: dict):
    """The single (arrays + scalars) -> EncodedHistory/WrEncoded
    reconstruction, shared by the shm transport's materialize and the
    sidecar cache loader — one place owns the op_index aliasing and
    the edges re-tupling, so the two zero-copy paths can't drift."""
    if checker == "wr":
        from .checker.elle.wr import WrEncoded
        enc = WrEncoded()
        enc.n = int(meta["n"])
        enc.key_count = int(meta["key_count"])
        enc.edges = [tuple(r) for r in arrays["edges"].tolist()]
    else:
        from .checker.elle.encode import EncodedHistory
        enc = EncodedHistory()
        enc.n = int(meta["n"])
        enc.n_keys = int(meta["n_keys"])
        enc.max_pos = int(meta["max_pos"])
        enc.key_names = meta["key_names"]
        enc.appends = arrays["appends"]
        enc.reads = arrays["reads"]
        enc.op_index = arrays["complete_index"]
    enc.status = arrays["status"]
    enc.process = arrays["process"]
    enc.invoke_index = arrays["invoke_index"]
    enc.complete_index = arrays["complete_index"]
    enc.anomalies = meta["anomalies"]
    enc.txn_ops = []
    return enc

# Bounded content hash: first + last 64KiB (whole file when smaller).
# Histories are append-only artifacts — corruption or rewrite shows up
# at one end — and an unbounded hash would put a full file read back on
# the path the cache exists to remove.
_HASH_SPAN = 64 * 1024

_X1 = 0x9E3779B185EBCA87
_X2 = 0xC2B2AE3D27D4EB4F
_X3 = 0x165667B19E3779F9
_X4 = 0x85EBCA77C2B2AE63
_X5 = 0x27D4EB2F165667C5
_M64 = (1 << 64) - 1


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def xxh64(data: bytes, seed: int = 0) -> int:
    """Pure-Python XXH64 (bit-exact with the reference algorithm and
    with native/hist_encode.cc's jt_xxh64_buf — parity is
    differentially tested). This is the FALLBACK and parity oracle:
    _buf_xxh64 routes cache keying through the native hasher when the
    .so is loaded (the Python loop costs ~30ms per 128KiB window —
    real money on the warm path this hash gates)."""
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + _X1 + _X2) & _M64
        v2 = (seed + _X2) & _M64
        v3 = seed & _M64
        v4 = (seed - _X1) & _M64
        while i + 32 <= n:
            for off, v in ((0, v1), (8, v2), (16, v3), (24, v4)):
                lane = int.from_bytes(data[i + off:i + off + 8],
                                      "little")
                v = (_rotl((v + lane * _X2) & _M64, 31) * _X1) & _M64
                if off == 0:
                    v1 = v
                elif off == 8:
                    v2 = v
                elif off == 16:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12)
             + _rotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h ^= (_rotl((v * _X2) & _M64, 31) * _X1) & _M64
            h = (h * _X1 + _X4) & _M64
    else:
        h = (seed + _X5) & _M64
    h = (h + n) & _M64
    while i + 8 <= n:
        k = (int.from_bytes(data[i:i + 8], "little") * _X2) & _M64
        h ^= (_rotl(k, 31) * _X1) & _M64
        h = (_rotl(h, 27) * _X1 + _X4) & _M64
        i += 8
    if i + 4 <= n:
        h ^= (int.from_bytes(data[i:i + 4], "little") * _X1) & _M64
        h = (_rotl(h, 23) * _X2 + _X3) & _M64
        i += 4
    while i < n:
        h ^= (data[i] * _X5) & _M64
        h = (_rotl(h, 11) * _X1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * _X2) & _M64
    h ^= h >> 29
    h = (h * _X3) & _M64
    h ^= h >> 32
    return h


def _buf_xxh64(data: bytes) -> int:
    """XXH64 via the native library when loaded (one C call), pure
    Python otherwise — both bit-identical, so cache keys don't depend
    on which side hashed."""
    try:
        from . import native_lib
        L = native_lib.hist_lib()
        if L is not None:
            return L.jt_xxh64_buf(data, len(data), 0)
    except Exception:
        pass
    return xxh64(data)


def shard_of(key: str, n_shards: int) -> int:
    """Deterministic shard assignment for a run dir: a stable hash of
    the store-relative run key (``<test-name>/<start-time>`` — the
    same string the verdict journal records), so every host of a mesh
    sweep derives the SAME partition from nothing but the store
    listing, and the partition survives the store moving between
    hosts or sweeps. xxh64 keeps it independent of PYTHONHASHSEED and
    bit-identical whether the native or the Python hasher computed
    it."""
    if n_shards <= 1:
        return 0
    return _buf_xxh64(str(key).encode()) % n_shards


def bounded_file_xxh64(path: Path, size: int) -> int:
    """xxh64 over the first + last _HASH_SPAN bytes (whole file when
    it fits in one window pair) — the content part of the cache key.
    Must stay byte-identical to the C++ side's file_cache_key()."""
    with open(path, "rb") as f:
        if size <= 2 * _HASH_SPAN:
            data = f.read()
        else:
            head = f.read(_HASH_SPAN)
            f.seek(size - _HASH_SPAN)
            data = head + f.read(_HASH_SPAN)
    return _buf_xxh64(data)


def encode_cache_enabled() -> bool:
    """The JEPSEN_TPU_ENCODE_CACHE master gate (default on)."""
    from . import gates
    return gates.get("JEPSEN_TPU_ENCODE_CACHE")


def encode_cache_write_enabled() -> bool:
    """JEPSEN_TPU_ENCODE_CACHE_WRITE=0 makes the cache read-only
    (e.g. sweeping a store on a read-only mount)."""
    from . import gates
    return gates.get("JEPSEN_TPU_ENCODE_CACHE_WRITE")


def sidecar_v2_enabled() -> bool:
    """One home for the JEPSEN_TPU_SIDECAR_V2 gate (default on):
    append sidecars are written dispatch-shaped (encoded.v2.bin) and
    v1 sidecars upgrade in place on load. 0 pins the v1 format."""
    from . import gates
    return gates.get("JEPSEN_TPU_SIDECAR_V2")


def sidecar_version(checker: str) -> int:
    """The sidecar version the current env writes for `checker`: v2 is
    append-only (the wr edge packer has no padded fast path)."""
    return 2 if checker == "append" and sidecar_v2_enabled() else 1


def encoded_cache_path(run_dir: str | os.PathLike, checker: str,
                       version: int | None = None) -> Path:
    """The per-checker sidecar path: append and wr digests of the same
    history are different tensors, so they cache separately. `version`
    defaults to what the env would write (`sidecar_version`)."""
    if version is None:
        version = sidecar_version(checker)
    name = f"encoded.v{version}.bin" if checker == "append" \
        else f"encoded-{checker}.v1.bin"
    return Path(run_dir) / name


def _history_source(run_dir: Path) -> Path | None:
    """The file the cache key covers — the same preference order as
    load_history_dir, so the cache can never validate against a file
    the encode wouldn't have read."""
    jl = run_dir / "history.jsonl"
    if jl.is_file():
        return jl
    ed = run_dir / "history.edn"
    return ed if ed.is_file() else None


def _cache_key(src: Path) -> dict:
    st = src.stat()
    return {"size": st.st_size, "mtime_ns": st.st_mtime_ns,
            "xxh64": f"{bounded_file_xxh64(src, st.st_size):016x}"}


def _align64(n: int) -> int:
    return (n + 63) & ~63


def _padded_arrays(enc, pad: dict) -> list:
    """[(field, contiguous ndarray)] for the v2 (dispatch-shaped)
    sidecar: the lean arrays padded to `pad` with pack_batch's fill
    convention (-1 dead triples/process rows, 0 dead index rows), plus
    the two device-dtype dispatch tensors pack_batch would otherwise
    compute per sweep — int32 invoke keys and int32 EFFECTIVE
    completion keys (effective_complete_index precomputed, so the
    warm path never touches `status` on the host)."""
    import numpy as np

    from .checker.elle.encode import effective_complete_index
    T, A, R = pad["n_txns"], pad["n_appends"], pad["n_reads"]
    n = enc.n
    appends = np.full((A, 3), -1, np.int32)
    appends[:len(enc.appends)] = np.asarray(enc.appends,
                                            np.int32).reshape(-1, 3)
    reads = np.full((R, 3), -1, np.int32)
    reads[:len(enc.reads)] = np.asarray(enc.reads,
                                        np.int32).reshape(-1, 3)
    process = np.full(T, -1, np.int32)
    process[:n] = np.asarray(enc.process, np.int32)
    d_invoke = np.zeros(T, np.int32)
    d_invoke[:n] = np.asarray(enc.invoke_index, np.int32)
    d_complete = np.zeros(T, np.int32)
    d_complete[:n] = effective_complete_index(
        np.asarray(enc.status, np.int32),
        np.asarray(enc.complete_index, np.int64)).astype(np.int32)
    return [("appends", appends), ("reads", reads),
            ("status", np.ascontiguousarray(enc.status, np.int32)),
            ("process", process),
            ("invoke_index",
             np.ascontiguousarray(enc.invoke_index, np.int64)),
            ("complete_index",
             np.ascontiguousarray(enc.complete_index, np.int64)),
            ("d_invoke", d_invoke), ("d_complete", d_complete)]


def save_encoded(run_dir: str | os.PathLike, checker: str,
                 enc) -> Path | None:
    """Write the flat encoded sidecar for a LEAN encoding (v2 when
    `sidecar_version(checker)` says so, else v1). Best-effort: any
    failure (non-JSON-able keys, read-only dir) returns None and the
    run simply stays uncached. Layout — magic, int64 header length,
    JSON header, zero pad to 64, then each tensor raw at the
    64-aligned offset its header entry records (relative to the data
    start, itself align64(16 + header_len)). A successful v2 write
    also retires the run's v1 sidecar: two sidecars answering the same
    key would just double the invalidation surface."""
    if not (encode_cache_enabled() and encode_cache_write_enabled()):
        return None
    d = Path(run_dir)
    src = _history_source(d)
    if src is None:
        return None
    version = sidecar_version(checker)
    tmp = None
    try:
        if version == 2:
            pad = dispatch_pad_plan(enc)
            arrays = _padded_arrays(enc, pad)
            meta = {"n": enc.n, "n_keys": enc.n_keys,
                    "max_pos": enc.max_pos,
                    "key_names": list(enc.key_names),
                    "pad": pad,
                    "lens": {"appends": len(enc.appends),
                             "reads": len(enc.reads)}}
            magic = ENCODED_MAGIC_V2
        else:
            arrays = encoded_arrays(enc, checker)
            if checker == "wr":
                meta = {"n": enc.n, "key_count": enc.key_count}
            else:
                meta = {"n": enc.n, "n_keys": enc.n_keys,
                        "max_pos": enc.max_pos,
                        "key_names": list(enc.key_names)}
            magic = ENCODED_MAGIC
        off = 0
        entries = {}
        for name, a in arrays:
            off = _align64(off)
            entries[name] = [off, list(a.shape), a.dtype.str]
            off += a.nbytes
        header = {"v": version, "checker": checker, "src": src.name,
                  "key": _cache_key(src), "arrays": entries,
                  "anomalies": enc.anomalies, **meta}
        hj = json.dumps(header).encode()
        data_start = _align64(len(magic) + 8 + len(hj))
        out = encoded_cache_path(d, checker, version)
        tmp = out.with_name(f".{out.name}.{os.getpid()}.tmp")
        with open(tmp, "wb") as f:
            f.write(magic)
            f.write(len(hj).to_bytes(8, "little"))
            f.write(hj)
            f.write(b"\0" * (data_start - len(magic) - 8
                             - len(hj)))
            pos = 0
            for name, a in arrays:
                aligned = _align64(pos)
                f.write(b"\0" * (aligned - pos))
                f.write(memoryview(a).cast("B") if a.nbytes else b"")
                pos = aligned + a.nbytes
        os.replace(tmp, out)
        if version == 2:
            try:
                encoded_cache_path(d, checker, 1).unlink()
            except OSError:
                pass
        return out
    except Exception:
        log.debug("encoded-cache write failed for %s", d, exc_info=True)
        try:
            if tmp is not None:
                tmp.unlink(missing_ok=True)
        except Exception:
            pass
        return None


def load_encoded(run_dir: str | os.PathLike, checker: str):
    """mmap the encoded sidecar back into an EncodedHistory/WrEncoded
    (zero-copy views over the mapped pages), or None on miss: no
    sidecar, stale key (history changed), wrong checker, or any parse
    failure. Prefers the dispatch-shaped v2 sidecar when the gate is
    on (the returned encoding then carries `.dispatch` — pre-padded
    mmap views the batch packer can feed to device_put copy-free —
    and `.dispatch_pad`, the geometry they were padded to); a v1-only
    run upgrades to v2 in place on the way through. Every cache-loaded
    encoding is flagged `.warm = True` so the pack stage can attribute
    `warm_copy_bytes` honestly."""
    if not encode_cache_enabled():
        return None
    d = Path(run_dir)
    src = _history_source(d)
    if src is None:
        return None
    want_v2 = sidecar_version(checker) == 2
    if want_v2:
        enc = _load_sidecar(encoded_cache_path(d, checker, 2), 2,
                            checker, src)
        if enc is not None:
            return enc
    enc = _load_sidecar(encoded_cache_path(d, checker, 1), 1,
                        checker, src)
    if enc is None:
        return None
    if want_v2 and encode_cache_write_enabled():
        enc = _upgrade_sidecar(d, checker, enc)
    return enc


def _upgrade_sidecar(run_dir: Path, checker: str, enc):
    """v1 → v2 in place: rewrite the sidecar dispatch-shaped and serve
    the v2 views. A failed write (read-only mount) keeps serving the
    v1 encoding — the upgrade is an optimization, never a gate."""
    out = save_encoded(run_dir, checker, enc)
    if out is None:
        return enc
    from . import trace
    trace.counter("sidecar_upgrades").inc()
    from .obs import events as obs_events
    obs_events.emit("cache_rebuild", path=str(out),
                    cause="v1->v2 upgrade")
    src = _history_source(Path(run_dir))
    enc2 = _load_sidecar(out, 2, checker, src) if src is not None \
        else None
    if enc2 is not None:
        # pool workers' tracers/events are process-local and never
        # exported: flag the encoding so ingest can relay the upgrade
        # to the PARENT's counter + flight recorder (info["upgraded"])
        enc2.upgraded = True
        return enc2
    return enc


def _load_sidecar(p: Path, version: int, checker: str, src: Path):
    """One sidecar file → encoding, or None on miss/corruption.
    Handles both writer dialects at either version — the Python writer
    embeds lean anomalies as JSON; the native writer stores raw
    anomaly rows + the pre-key name table, decoded here with the exact
    `_witness` mapping the in-process native path uses."""
    if not p.is_file():
        return None
    magic = ENCODED_MAGIC_V2 if version == 2 else ENCODED_MAGIC
    try:
        import mmap as _mmap

        import numpy as np

        from .util import with_retry

        def _map():
            with open(p, "rb") as f:
                return _mmap.mmap(f.fileno(), 0,
                                  access=_mmap.ACCESS_READ)

        # transient open/mmap failures (EMFILE/ENOMEM under a big
        # sweep's pressure) get a short jittered retry before the
        # cache degrades to a miss; a vanished sidecar fails straight
        # to the (cheap, correct) re-encode path
        mm = with_retry(_map, retries=2, backoff=0.005,
                        exceptions=(OSError,), exponential=True,
                        fatal=(FileNotFoundError,))
        if mm[:len(magic)] != magic:
            # an existing sidecar without the magic is corruption, not
            # a miss — the flight recorder gets the rebuild cause
            from .obs import events as obs_events
            obs_events.emit("cache_rebuild", path=str(p),
                            cause="bad magic")
            return None
        hlen = int.from_bytes(
            mm[len(magic):len(magic) + 8], "little")
        header = json.loads(
            mm[len(magic) + 8:len(magic) + 8 + hlen])
        if header.get("v") != version \
                or header.get("checker") != checker \
                or header.get("src") != src.name:
            return None
        if header.get("key") != _cache_key(src):
            return None
        data_start = _align64(len(magic) + 8 + hlen)
        arrays = {}
        for name, (off, shape, dt) in header["arrays"].items():
            n = 1
            for s in shape:
                n *= s
            arrays[name] = np.frombuffer(
                mm, dtype=np.dtype(dt), count=n,
                offset=data_start + off).reshape(shape)
        pre_names = header.get("pre_names", [])
        if "anomalies" in header:
            anomalies = header["anomalies"]
        else:
            # native-written sidecar: raw anomaly rows, decoded with
            # the exact _witness mapping the in-process native path
            # uses, so cache-loaded == freshly-encoded
            from .checker.elle.native_encode import _CODES, _witness
            anomalies = {}
            for code, f0, f1, f2, f3 in arrays.pop("anom").tolist():
                name = _CODES.get(int(code))
                if name is None:    # ABI drift: don't guess
                    return None
                anomalies.setdefault(name, []).append(
                    _witness(int(code), int(f0), int(f1), int(f2),
                             int(f3), pre_names, wr=checker == "wr"))
        meta = {k: header[k] for k in ("n", "n_keys", "max_pos",
                                       "key_count") if k in header}
        meta["anomalies"] = anomalies
        if checker != "wr":
            meta["key_names"] = header["key_names"] \
                if "key_names" in header else \
                [pre_names[i] for i in arrays.pop("kid_to_pre").tolist()]
        if version == 2:
            enc = _rebuild_v2(arrays, meta, header)
        else:
            enc = rebuild_encoded(checker, arrays, meta)
        enc.warm = True
        return enc
    except Exception as e:
        log.debug("encoded-cache load failed for %s", p, exc_info=True)
        from .obs import events as obs_events
        obs_events.emit("cache_rebuild", path=str(p),
                        cause=repr(e)[:200])
        return None


def _rebuild_v2(arrays: dict, meta: dict, header: dict):
    """(padded mmap arrays + scalars) → EncodedHistory whose lean
    fields are SLICES of the padded tensors and whose `.dispatch` dict
    holds the full dispatch-shaped views (pack order: appends, reads,
    invoke, complete(effective), process) ready for device_put."""
    from .checker.elle.encode import EncodedHistory
    n = int(meta["n"])
    lens = header["lens"]
    pad = header["pad"]
    enc = EncodedHistory()
    enc.n = n
    enc.n_keys = int(meta["n_keys"])
    enc.max_pos = int(meta["max_pos"])
    enc.key_names = meta["key_names"]
    enc.appends = arrays["appends"][:int(lens["appends"])]
    enc.reads = arrays["reads"][:int(lens["reads"])]
    enc.status = arrays["status"]
    enc.process = arrays["process"][:n]
    enc.invoke_index = arrays["invoke_index"]
    enc.complete_index = arrays["complete_index"]
    enc.op_index = arrays["complete_index"]
    enc.anomalies = meta["anomalies"]
    enc.txn_ops = []
    enc.dispatch = {"appends": arrays["appends"],
                    "reads": arrays["reads"],
                    "invoke_index": arrays["d_invoke"],
                    "complete_index": arrays["d_complete"],
                    "process": arrays["process"]}
    enc.dispatch_pad = {k: int(v) for k, v in pad.items()}
    return enc


def _results_to_edn(v: Any) -> Any:
    """Convert a results dict (string keys) to EDN with keyword keys."""
    if isinstance(v, dict):
        return {edn.Keyword(str(k)) if isinstance(k, str) else k:
                _results_to_edn(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_results_to_edn(x) for x in v]
    if isinstance(v, bool) or v is None or isinstance(v, (int, float)):
        return v
    if isinstance(v, (set, frozenset)):
        return frozenset(_results_to_edn(x) for x in v)
    if isinstance(v, str):
        return edn.Keyword(v) if v in ("unknown", "valid", "invalid") else v
    return repr(v)
