"""Persistence: the on-disk store of test runs.

Layout mirrors the reference (jepsen/src/jepsen/store.clj:29,118-140):

    store/<test-name>/<start-time>/
        history.edn     one op map per line (reference-compatible)
        history.jsonl   same ops as JSON lines (fast native load path)
        test.json       the serializable test map
        results.edn     checker verdict (reference-compatible)
        results.json    same verdict as JSON
        jepsen.log      run log
        ...             checker artifacts (plots, timelines)

plus `current`/`latest` symlinks at both the store root and the test dir
(store.clj:307-333). `save_1` persists the test+history before analysis so a
crash during checking never loses data (core.clj:630); `save_2` adds results
(store.clj:385-397).
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import shutil
from pathlib import Path
from typing import Any, Iterable

from . import edn, history as h
from .util import chunk_vec, real_pmap

log = logging.getLogger(__name__)

# Keys that never serialize (functions, live connections...).
# Reference: store.clj:160-168.
NONSERIALIZABLE_KEYS = (
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "remote", "store", "logging", "barrier", "sessions", "args",
)

DEFAULT_BASE = "store"

# History chunks are written in parallel above this size
# (reference util.clj:208: threshold 16,384 ops).
PARALLEL_WRITE_THRESHOLD = 16384


def _stringify(v: Any) -> Any:
    """Best-effort conversion of a test-map value to JSON-compatible data."""
    if isinstance(v, dict):
        return {str(k): _stringify(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_stringify(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted((_stringify(x) for x in v), key=repr)
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, datetime.datetime):
        return v.isoformat()
    return repr(v)


def load_history_dir(run_dir: str | os.PathLike) -> list[h.Op]:
    """History ops from a run dir: history.jsonl preferred,
    reference-format history.edn fallback. Module-level (not a Store
    method) so encode-only worker processes can load runs without
    constructing a store."""
    d = Path(run_dir)
    jl = d / "history.jsonl"
    if jl.exists():
        # one json.loads over a joined array is ~2.3x faster than a
        # loads per line — ingest parse is the dominant host cost of
        # big store sweeps
        lines = [ln for ln in jl.read_text().splitlines() if ln.strip()]
        if not lines:
            return []
        return json.loads("[" + ",".join(lines) + "]")
    ed = d / "history.edn"
    if ed.exists():
        return h.history_from_edn(ed.read_text())
    raise FileNotFoundError(f"no history in {d}")


class Store:
    """A store rooted at `base` (default ./store)."""

    def __init__(self, base: str | os.PathLike = DEFAULT_BASE):
        self.base = Path(base)

    # -- paths ------------------------------------------------------------

    def test_dir(self, test: dict) -> Path:
        name = test.get("name", "noname")
        start = test.get("start-time")
        if start is None:
            start = datetime.datetime.now().strftime("%Y%m%dT%H%M%S.%f")[:-3]
            test["start-time"] = start
        return self.base / name / str(start)

    def path(self, test: dict, *parts: str) -> Path:
        p = self.test_dir(test).joinpath(*parts)
        p.parent.mkdir(parents=True, exist_ok=True)
        return p

    # -- symlinks (store.clj:307-333) --------------------------------------

    def _relink(self, link: Path, target: Path) -> None:
        link.parent.mkdir(parents=True, exist_ok=True)
        if link.is_symlink() or link.exists():
            # Only move forward: re-analyzing an OLD run (analyze-store's
            # sweep) must not steal latest/current from a newer run.
            try:
                if link.resolve().name > target.name:
                    return
            except OSError:
                pass
            link.unlink()
        link.symlink_to(os.path.relpath(target, link.parent))

    def update_symlinks(self, test: dict) -> None:
        d = self.test_dir(test)
        self._relink(d.parent / "latest", d)
        self._relink(self.base / "latest", d)
        self._relink(self.base / "current", d)

    # -- writes -----------------------------------------------------------

    def write_history(self, test: dict) -> None:
        hist = test.get("history", [])
        d = self.test_dir(test)
        d.mkdir(parents=True, exist_ok=True)
        if len(hist) > PARALLEL_WRITE_THRESHOLD:
            chunks = chunk_vec(PARALLEL_WRITE_THRESHOLD, hist)
            parts = real_pmap(
                lambda c: (h.history_to_edn(c),
                           "".join(json.dumps(_stringify(o)) + "\n" for o in c)),
                chunks)
            with open(d / "history.edn", "w") as fe, \
                 open(d / "history.jsonl", "w") as fj:
                for e_part, j_part in parts:
                    fe.write(e_part)
                    fj.write(j_part)
        else:
            (d / "history.edn").write_text(h.history_to_edn(hist) if hist else "")
            (d / "history.jsonl").write_text(
                "".join(json.dumps(_stringify(o)) + "\n" for o in hist))

    def write_test(self, test: dict) -> None:
        t = {k: _stringify(v) for k, v in test.items()
             if k not in NONSERIALIZABLE_KEYS and k not in ("history", "results")}
        p = self.path(test, "test.json")
        p.write_text(json.dumps(t, indent=2, default=repr))

    def write_results(self, test: dict) -> None:
        res = test.get("results", {})
        self.path(test, "results.json").write_text(
            json.dumps(_stringify(res), indent=2, default=repr))
        self.path(test, "results.edn").write_text(
            edn.dumps(_results_to_edn(res)) + "\n")

    def save_1(self, test: dict) -> dict:
        """Persist test + history (before analysis)."""
        self.write_test(test)
        self.write_history(test)
        self.update_symlinks(test)
        return test

    def write_trace(self, test: dict) -> Path | None:
        """Persist the current run tracer's `trace.json` (Chrome
        trace-event format, Perfetto-loadable) and `metrics.json` next
        to history.edn — every run self-attributes, not just benches.
        No-op (returns None) when tracing is disabled
        (JEPSEN_TPU_TRACE=0 / --no-trace), or when the current tracer
        is sweep-scoped (analyze-store fallbacks re-analyze runs under
        the SWEEP's tracer; exporting it here would write the whole
        sweep's events into each run dir, once per run)."""
        from . import trace
        t = trace.get_current()
        if not getattr(t, "enabled", False) \
                or getattr(t, "scope", "run") != "run":
            return None
        d = self.test_dir(test)
        d.mkdir(parents=True, exist_ok=True)
        p = t.export(d / "trace.json")
        t.export_metrics(d / "metrics.json")
        return p

    def save_2(self, test: dict) -> dict:
        """Persist results (after analysis), plus the run's trace +
        metrics artifacts (observability must never sink persistence,
        so trace export failures degrade to a warning)."""
        self.write_test(test)
        self.write_results(test)
        try:
            self.write_trace(test)
        except Exception:
            log.warning("trace/metrics export failed", exc_info=True)
        self.update_symlinks(test)
        return test

    # -- reads ------------------------------------------------------------

    def tests(self) -> dict[str, dict[str, Path]]:
        """Map of test-name -> {start-time -> dir} (store.clj:275)."""
        out: dict[str, dict[str, Path]] = {}
        if not self.base.exists():
            return out
        for name_dir in sorted(self.base.iterdir()):
            if not name_dir.is_dir() or name_dir.name in ("latest", "current"):
                continue
            runs = {d.name: d for d in sorted(name_dir.iterdir())
                    if d.is_dir() and d.name != "latest"}
            if runs:
                out[name_dir.name] = runs
        return out

    def all_run_dirs(self) -> list[Path]:
        return [d for runs in self.tests().values() for d in runs.values()]

    def latest(self) -> Path | None:
        link = self.base / "latest"
        if link.exists():
            return link.resolve()
        dirs = self.all_run_dirs()
        # Most recent start-time across all test names.
        return max(dirs, key=lambda d: d.name) if dirs else None

    def load_history(self, run_dir: str | os.PathLike) -> list[h.Op]:
        """Load a history from a run dir: prefers history.jsonl, falls back
        to reference-format history.edn."""
        return load_history_dir(run_dir)

    def load_test(self, run_dir: str | os.PathLike) -> dict:
        """Load a run dir — ours (test.json) or the reference's
        (test.fressian, store.clj:372-383)."""
        # Resolve symlinks (latest/current) so the dir name below is the
        # real timestamp, not "latest" — re-linking against the link name
        # would create a self-loop.
        d = Path(run_dir).resolve()
        test: dict = {}
        tj = d / "test.json"
        tf = d / "test.fressian"
        if tj.exists():
            test = json.loads(tj.read_text())
        elif tf.exists():
            from . import fressian
            raw = fressian.load_test(tf)
            if isinstance(raw, dict):
                # Map keys are edn.Keyword, which subclasses str and
                # equals its bare name — str() normalizes them.
                test = {str(k): v for k, v in raw.items()}
        # The run dir is authoritative for name/start-time so re-analysis
        # writes back into the SAME dir (cli.clj analyze, :381-411),
        # whatever form the serialized test map stored them in.
        test["start-time"] = d.name
        test.setdefault("name", d.parent.name)
        test["history"] = self.load_history(d)
        rj = d / "results.json"
        if rj.exists():
            try:
                test["results"] = json.loads(rj.read_text())
            except (OSError, json.JSONDecodeError):
                # results are a derived artifact: a write truncated by
                # a crash must not make the run unloadable (re-analysis
                # regenerates it)
                pass
        return test

    def load_results(self, run_dir: str | os.PathLike) -> dict | None:
        d = Path(run_dir)
        rj = d / "results.json"
        if rj.exists():
            try:
                return json.loads(rj.read_text())
            except (OSError, json.JSONDecodeError):
                pass
        re_ = d / "results.edn"
        if re_.exists():
            v = edn.loads(re_.read_text())
            return v if isinstance(v, dict) else None
        return None

    def delete(self, name: str | None = None) -> None:
        """Delete a test's runs (or the whole store)."""
        target = self.base / name if name else self.base
        if target.exists():
            shutil.rmtree(target)


def _results_to_edn(v: Any) -> Any:
    """Convert a results dict (string keys) to EDN with keyword keys."""
    if isinstance(v, dict):
        return {edn.Keyword(str(k)) if isinstance(k, str) else k:
                _results_to_edn(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_results_to_edn(x) for x in v]
    if isinstance(v, bool) or v is None or isinstance(v, (int, float)):
        return v
    if isinstance(v, (set, frozenset)):
        return frozenset(_results_to_edn(x) for x in v)
    if isinstance(v, str):
        return edn.Keyword(v) if v in ("unknown", "valid", "invalid") else v
    return repr(v)
