"""`make bench-warm` — the copy-free warm-path gate.

Runs the smoke-shape cold → warm → warm-again sequence, each sweep in
its OWN process over a shared store and a shared executable-cache
directory, and fails (exit 1) unless the third run proves the warm
path is actually copy-free:

  * `warm_copy_bytes == 0` — every bucket fed `device_put` straight
    from the v2 sidecar's mmap views, no host-side pack copies;
  * `compile_cache_misses == 0` — every dispatch came out of the
    persistent AOT executable cache, zero XLA compiles;
  * verdicts byte-identical across all three runs (the parity floor —
    a fast wrong answer is not a win).

Separate processes are the point: the second warm run starts with an
empty in-memory jit cache and an empty in-memory AOT map, so its 100%
hit rate can only come from the disk layer. One JSON line per run and
one summary line out, `python -m jepsen_tpu.warm_bench` to run by
hand (BENCH_WARM_B/T/K scale the shape).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path


def _write_store(root: Path, B: int, T: int, K: int) -> list[Path]:
    """B serial list-append run dirs (the bench's north-star execution
    shape via the SHARED generator, scaled to smoke size), the last
    one seeded with a G1c cycle so the classify path runs too."""
    from jepsen_tpu.checker.elle.synth import write_synth_store
    return write_synth_store(root, B, T, K, bad_every=B)


def _child(store_dir: str) -> int:
    """One sweep over the store; prints counters + a verdict digest."""
    import time

    from jepsen_tpu import ingest, parallel, trace

    tr = trace.fresh_run("warm-bench")

    def ctr(name: str) -> int:
        return getattr(tr.counter(name), "value", 0) or 0

    dirs = sorted(Path(store_dir).iterdir())
    t0 = time.perf_counter()
    encs = [ingest.encode_run_dir(d, "append") for d in dirs]
    t_ingest = time.perf_counter() - t0
    bad = [e for e in encs if isinstance(e, Exception)]
    assert not bad, bad[:1]
    t0 = time.perf_counter()
    verdicts = parallel.check_bucketed(encs)
    t_check = time.perf_counter() - t0
    digest = hashlib.sha256(
        json.dumps([sorted(v) for v in verdicts]).encode()).hexdigest()
    print(json.dumps({
        "ingest_secs": round(t_ingest, 3),
        "check_secs": round(t_check, 3),
        "verdict_digest": digest,
        "invalid": sum(1 for v in verdicts if v),
        "warm_copy_bytes": ctr("warm_copy_bytes"),
        "h2d_bytes": ctr("h2d_bytes"),
        "compile_cache_hits": ctr("compile_cache_hits"),
        "compile_cache_misses": ctr("compile_cache_misses"),
        "buffers_donated": ctr("buffers_donated"),
        "cache_hits": ctr("cache_hits"),
        "cache_misses": ctr("cache_misses"),
    }))
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        return _child(argv[1])

    B = int(os.environ.get("BENCH_WARM_B", 6))
    T = int(os.environ.get("BENCH_WARM_T", 60))
    K = int(os.environ.get("BENCH_WARM_K", 8))
    with tempfile.TemporaryDirectory(prefix="bench-warm-") as td:
        store_dir = Path(td) / "store"
        store_dir.mkdir()
        _write_store(store_dir, B, T, K)
        env = {**os.environ,
               "JEPSEN_TPU_COMPILE_CACHE_DIR": str(Path(td) / "aot"),
               "JEPSEN_TPU_TRACE": "1"}
        runs = []
        for name in ("cold", "warm", "warm-again"):
            p = subprocess.run(
                [sys.executable, "-m", "jepsen_tpu.warm_bench",
                 "--child", str(store_dir)],
                capture_output=True, text=True, timeout=600, env=env)
            got = None
            for line in reversed((p.stdout or "").strip().splitlines()):
                try:
                    got = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            if got is None:
                print(f"bench-warm: {name} run produced no JSON "
                      f"(rc={p.returncode}): "
                      + (p.stderr or "")[-300:], file=sys.stderr)
                return 1
            got["run"] = name
            runs.append(got)
            print(json.dumps(got))

        failures = []
        if len({r["verdict_digest"] for r in runs}) != 1:
            failures.append("verdicts differ across cold/warm runs")
        last = runs[-1]
        if last["warm_copy_bytes"] != 0:
            failures.append(
                f"warm-again copied {last['warm_copy_bytes']} host "
                "bytes (want 0: pack must feed device_put from the "
                "v2 sidecar mmap)")
        if last["compile_cache_misses"] != 0:
            failures.append(
                f"warm-again missed the executable cache "
                f"{last['compile_cache_misses']} time(s) (want 0: a "
                "repeat sweep pays zero XLA compiles)")
        if last["cache_misses"] != 0:
            failures.append(
                f"warm-again re-encoded {last['cache_misses']} "
                "run(s) (want 0: every history hits its sidecar)")
        if failures:
            for f in failures:
                print(f"bench-warm: FAIL: {f}", file=sys.stderr)
            return 1
        print(f"bench-warm: OK — {B}x{T}-txn smoke store: warm path "
              f"copy-free (warm_copy_bytes=0), "
              f"{last['compile_cache_hits']} executable-cache hits, "
              "0 misses, verdicts byte-identical")
        return 0


if __name__ == "__main__":
    sys.exit(main())
