"""Interactive helpers for poking at stored runs.

Counterpart of jepsen.repl (jepsen/src/jepsen/repl.clj:6-13) plus the
report/codec odds and ends (report.clj, codec.clj)."""

from __future__ import annotations

import contextlib
from typing import Any

from . import edn
from .store import Store


def last_test(store: Store | str = "store") -> dict | None:
    """Load the most recently run test (repl.clj:6-13)."""
    st = store if isinstance(store, Store) else Store(store)
    d = st.latest()
    return None if d is None else st.load_test(d)


@contextlib.contextmanager
def to_file(path):
    """Redirect stdout into a file — the reference's report/to macro
    (report.clj:9-16): parents created, and a 'Report written to'
    notice printed on the REAL stdout afterwards."""
    import os
    path = os.fspath(path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    # Like the reference, the notice prints once the file is OPEN (its
    # finally sits inside with-open): never for an unopenable path.
    with open(path, "w") as f:
        try:
            with contextlib.redirect_stdout(f):
                yield f
        finally:
            print("Report written to", path)


# codec.clj:9-29: EDN <-> bytes.
def encode(value: Any) -> bytes:
    return edn.dumps(value).encode("utf-8")


def decode(data: bytes | None) -> Any:
    if data is None:
        return None
    return edn.loads(data.decode("utf-8"))
