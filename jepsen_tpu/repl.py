"""Interactive helpers for poking at stored runs.

Counterpart of jepsen.repl (jepsen/src/jepsen/repl.clj:6-13) plus the
report/codec odds and ends (report.clj, codec.clj)."""

from __future__ import annotations

import contextlib
from typing import Any

from . import edn
from .store import Store


def last_test(store: Store | str = "store") -> dict | None:
    """Load the most recently run test (repl.clj:6-13)."""
    st = store if isinstance(store, Store) else Store(store)
    d = st.latest()
    return None if d is None else st.load_test(d)


@contextlib.contextmanager
def to_file(path):
    """Redirect stdout into a file — the reference's report/to-file
    macro (report.clj:9-16)."""
    with open(path, "w") as f, contextlib.redirect_stdout(f):
        yield f


# codec.clj:9-29: EDN <-> bytes.
def encode(value: Any) -> bytes:
    return edn.dumps(value).encode("utf-8")


def decode(data: bytes | None) -> Any:
    if data is None:
        return None
    return edn.loads(data.decode("utf-8"))
