# Repo-level developer/CI entry points. External CI needs exactly one
# command per gate: `make lint` (static analysis, exit 0/1),
# `make test` (tier-1), `make native-sanitize` (dynamic analysis of
# the C++ layer).

PY ?= python
ASAN_RT := $(shell gcc -print-file-name=libasan.so)
TSAN_RT := $(shell gcc -print-file-name=libtsan.so)

.PHONY: lint lint-json lint-changed env-table rule-table dur-table \
	wire-table order-smoke \
	crash-smoke test native native-sanitize bench bench-report \
	bench-warm obs-smoke serve-smoke fleet-smoke trace-report \
	cost-report \
	search-report planner-report

# Self-hosted static analysis: gate registry, JAX hazards, concurrency
# discipline, shm lifecycle, tracer discipline, plus the cross-boundary
# analyses — ABI/layout prover, tensor-contract dataflow, lockset
# analysis, happens-before prover, frame-protocol drift
# (jepsen_tpu/lint/). order-smoke runs the two protocol families
# standalone first so their findings surface even if the full pass
# dies earlier.
lint: order-smoke
	$(PY) -m jepsen_tpu.cli lint

lint-json:
	$(PY) -m jepsen_tpu.cli lint --format json

# The fast inner loop: only files dirty vs the git merge-base, through
# the content-hash result cache (bench_artifacts/.lintcache). Full
# runs stay the tier-1 default.
lint-changed:
	$(PY) -m jepsen_tpu.cli lint --changed

# Regenerate the README rule table from the rule registry (lint rule
# JT-META-001 fails the build when the committed table drifts).
rule-table:
	$(PY) -c "from pathlib import Path; from jepsen_tpu import lint; \
	p = Path('README.md'); t = p.read_text(); \
	s = t.index(lint.RULES_BEGIN); \
	e = t.index(lint.RULES_END) + len(lint.RULES_END); \
	p.write_text(t[:s] + lint.render_rule_block() + t[e:]); \
	print('README.md rule table regenerated')"

# Regenerate the README env-gate table from the gates registry (lint
# rule JT-GATE-003 fails the build when the committed table drifts).
env-table:
	$(PY) -c "from pathlib import Path; from jepsen_tpu import gates; \
	p = Path('README.md'); t = p.read_text(); \
	s = t.index(gates.TABLE_BEGIN); \
	e = t.index(gates.TABLE_END) + len(gates.TABLE_END); \
	p.write_text(t[:s] + gates.render_env_block() + t[e:]); \
	print('README.md env-gate table regenerated')"

# Regenerate the README "Store durability" table from the
# STORE_ARTIFACTS registry (lint rule JT-DUR-006 fails the build when
# the committed table drifts).
dur-table:
	$(PY) -c "from pathlib import Path; \
	from jepsen_tpu.lint import contracts as c; \
	p = Path('README.md'); t = p.read_text(); \
	s = t.index(c.DUR_BEGIN); \
	e = t.index(c.DUR_END) + len(c.DUR_END); \
	p.write_text(t[:s] + c.render_dur_block() + t[e:]); \
	print('README.md store-durability table regenerated')"

# Regenerate the README wire-frame table from serve/protocol.py's
# FRAME_OPS registry (lint rule JT-WIRE-003 fails the build when the
# committed table drifts).
wire-table:
	$(PY) -c "from pathlib import Path; \
	from jepsen_tpu.lint import wireflow as w; \
	reg = w.live_registry(Path('.')); \
	p = Path('README.md'); t = p.read_text(); \
	s = t.index(w.WIRE_BEGIN); \
	e = t.index(w.WIRE_END) + len(w.WIRE_END); \
	p.write_text(t[:s] + w.render_wire_block(reg) + t[e:]); \
	print('README.md wire-frame table regenerated')"

# The two protocol families standalone against the live tree: JT-ORD
# module rules over the contracted modules, JT-WIRE project rules over
# the serve trio. Exit 1 on any finding.
order-smoke:
	$(PY) -c "import sys; from pathlib import Path; \
	from jepsen_tpu import lint; \
	from jepsen_tpu.lint import contracts, order, wireflow; \
	root = lint.default_root(); \
	files = sorted({root / c.file for c in contracts.ORDER_CONTRACTS}); \
	out = list(lint.lint_paths(files, root, rules=order.RULES)); \
	ctx = lint.ProjectCtx(root, []); \
	out += [f for r in wireflow.RULES for f in r.check_project(ctx)]; \
	[print(f.render()) for f in out]; \
	print(f'order-smoke: {len(out)} findings ' \
	      f'({len(contracts.ORDER_CONTRACTS)} contracts proved)'); \
	sys.exit(1 if out else 0)"

# Crash-consistency smoke: the kill-mid-write / short-write /
# torn-tail / rotation tests over the journal-class artifacts
# (costdb, verdict journal, events rotation) — the dynamic
# counterpart of the JT-DUR static prover.
crash-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_costdb.py \
	  tests/test_obs.py tests/test_durability_prover.py -q \
	  -m 'not slow' -k 'crash or torn or seal or rotat or caught'

# Tier-1: the ROADMAP verification gate.
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

native:
	$(MAKE) -C native

# Dynamic analysis of the native layer:
#   1. ASan+UBSan builds of hist_encode/wgl/graph_algo, replayed
#      through the existing differential encode tests with
#      JEPSEN_TPU_NATIVE_LIB_DIR pinning the instrumented .so's
#      (no silent fallback to a production build), plus the hostile-
#      input fuzz drive;
#   2. a TSan build of the encode/sidecar writer path hammered from
#      concurrent threads (native/asan_drive.py --tsan).
# detect_leaks=0: CPython's interpreter allocations drown the report;
# overflows/UB in the libraries still abort loudly.
native-sanitize:
	$(MAKE) -C native asan tsan
	LD_PRELOAD=$(ASAN_RT) ASAN_OPTIONS=detect_leaks=0 \
	  JEPSEN_TPU_NATIVE_LIB_DIR=native/build/asan JAX_PLATFORMS=cpu \
	  $(PY) -c "from jepsen_tpu import native_lib; \
	  assert native_lib.hist_lib() is not None, 'asan lib did not load'"
# TestHbmEnvelope is deselected: it exercises jitted bucket dispatch,
# and gcc-10 libasan's __cxa_throw interceptor CHECK-fails on
# exceptions unwound from jaxlib's statically-linked MLIR .so — a
# toolchain conflict, not a finding. Every test that touches the
# native encode/split/sidecar path stays in.
	LD_PRELOAD=$(ASAN_RT) ASAN_OPTIONS=detect_leaks=0 \
	  JEPSEN_TPU_NATIVE_LIB_DIR=native/build/asan JAX_PLATFORMS=cpu \
	  $(PY) -m pytest tests/test_ingest_pipeline.py \
	    tests/test_native_split.py -q -m 'not slow' \
	    -k 'not TestHbmEnvelope'
	LD_PRELOAD=$(ASAN_RT) ASAN_OPTIONS=detect_leaks=0 JAX_PLATFORMS=cpu \
	  $(PY) native/asan_drive.py
	LD_PRELOAD=$(TSAN_RT) TSAN_OPTIONS=halt_on_error=1 JAX_PLATFORMS=cpu \
	  $(PY) native/asan_drive.py --tsan

bench:
	JAX_PLATFORMS=cpu $(PY) bench.py

# The trajectory gate: trend table over the committed BENCH_*.json
# series, exit 1 when the latest round regresses past a declared
# threshold vs its same-backend predecessor.
bench-report:
	$(PY) -m jepsen_tpu.cli bench-report

# The copy-free warm-path gate: smoke-shape cold -> warm -> warm-again
# sweeps (each its own process, shared store + executable cache); fails
# if the second warm run copies any host bytes on the pack path or
# misses the AOT executable cache even once. Exit 0/1.
bench-warm:
	JAX_PLATFORMS=cpu $(PY) -m jepsen_tpu.warm_bench

# Live-telemetry + trace-fabric smoke: a tiny POOLED sweep with the
# health sampler, the /metrics endpoint and the attribution report
# force-enabled, one mid-flight scrape, an exposition<->metrics.json
# parity check, and the merged-trace/report contract (>=1 worker
# track with encode spans; shares sum to ~1.0). Exit 0/1.
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m jepsen_tpu.obs.smoke

# Verdict-service smoke: the REAL `jepsen-tpu serve` daemon as a
# subprocess over a synthetic store, two concurrent tenants through
# the real socket, a mid-flight /metrics scrape (per-tenant series),
# a SIGTERM drain (exit 0, zero lost/duplicated journal entries), and
# streamed-vs-`analyze-store` byte-identical verdict parity. Exit 0/1.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m jepsen_tpu.serve.smoke

# Serve-fleet smoke: a REAL 3-daemon fleet behind the router, three
# tenants streaming through `fleet.sock` while a self-nemesis schedule
# (socket partition, SIGKILL mid-load, SIGSTOP hammer, clock-skewed
# member via the faketime shim) breaks members underneath them. Every
# tenant must land every verdict with zero lost/duplicated journal
# lines, byte-identical to a post-hoc `analyze-store` sweep. Exit 0/1.
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m jepsen_tpu.serve.fleet_smoke

# Convenience: re-sweep an existing store (STORE ?= store) and emit
# the merged trace + critical-path attribution report
# (<store>/trace.json, report.json, report.md).
STORE ?= store
trace-report:
	$(PY) -m jepsen_tpu.cli analyze-store --store $(STORE) --report

# trace-report with the device cost observatory on: additionally
# appends per-(executable, geometry) XLA-cost × measured-window
# records to <store>/costdb.jsonl (provenance-tagged) and adds the
# device roofline section to the report.
cost-report:
	JEPSEN_TPU_COSTDB=1 \
	  $(PY) -m jepsen_tpu.cli analyze-store --store $(STORE) --report

# trace-report with kernel search telemetry on (and the costdb, so
# the search section's edge-density-vs-device-time join has measured
# windows): journals one stats line per history to
# <store>/analytics.jsonl and adds the "search" section (anomaly
# rate, closure-round + margin distributions) to the report.
search-report:
	JEPSEN_TPU_KERNEL_STATS=1 JEPSEN_TPU_COSTDB=1 \
	  $(PY) -m jepsen_tpu.cli analyze-store --store $(STORE) --report

# search-report with the cost-aware planner on: routes the sweep
# through the fitted model (warm-started from <store>/plan.json when
# one exists), refits the plan from this sweep's measured costdb ×
# analytics join at the end, and adds the "planner" section
# (decisions, fallbacks, predicted-vs-measured error) to the report.
planner-report:
	JEPSEN_TPU_PLANNER=1 JEPSEN_TPU_KERNEL_STATS=1 JEPSEN_TPU_COSTDB=1 \
	  $(PY) -m jepsen_tpu.cli analyze-store --store $(STORE) --report
